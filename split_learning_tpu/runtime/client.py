"""Multi-process protocol client: the reference's ``client.py`` +
``RpcClient`` + ``Train_*`` stack as one generic runner.

Lifecycle parity (``/root/reference/src/RpcClient.py:33-135``): REGISTER →
wait on ``reply_{id}`` → START builds the shard model and (stage 1) the
data loader → READY ack (replacing the reference's 25 s settle sleep,
``src/Server.py:289``) → SYN runs the streaming hot loop → NOTIFY/PAUSE →
UPDATE with the trained shard → STOP exits.

The hot loops reproduce the reference's three roles with ONE generic
:class:`ShardRunner` instead of three per-model ``Train_{VGG16,BERT,KWT}``
classes (``src/train/*.py``):

* stage 1 (``train_on_first_layer``, ``src/train/VGG16.py:61-136``):
  event-driven 1F1B with a bounded in-flight window (``control-count``)
  and backward-time activation **recomputation** — here the recompute is a
  jitted VJP that re-runs the forward inside the gradient computation,
  with the SAME dropout rng as the original forward (the reference
  redraws masks on recompute; re-using the rng makes the gradient exact);
* middle stages: trace-routed forward/backward relay
  (``src/train/VGG16.py:40-53``);
* last stage (``train_on_last_layer``, ``:138-191``): loss + backward,
  input-gradient returned along the popped trace; NaN flags the round
  (``:169-171``).  DCSL's server-side data aggregation — concatenate
  ``sda_size`` client batches into one fwd/bwd and split the input
  gradient back per client (``other/DCSL/src/Scheduler.py:152-191``) —
  is the same loop with a collect window.

Unlike the reference there is no 0.5 s sleep-polling: transport ``get``
blocks on a condition variable / socket (``runtime/bus.py``).
"""

from __future__ import annotations

import argparse
import dataclasses
import time
import uuid
import zlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax

from split_learning_tpu.config import Config, LearningConfig, from_yaml
from split_learning_tpu.data import make_data_loader, subset_seed
from split_learning_tpu.models import build_model
from split_learning_tpu.ops.lora import lora_init, lora_merge, split_frozen
from split_learning_tpu.runtime.bus import Transport
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.memo import bounded_setdefault
from split_learning_tpu.runtime.codec import make_codecs, wire_raw_nbytes
from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.protocol import (
    Activation, BlackboxDump, DigestRoute, EpochEnd, FrameAssembler,
    Gradient, Heartbeat, Notify, Pause, Ready, Register, SparseLeaf,
    Start, Stop, Syn, QuantLeaf, Update, aggregate_queue, encode,
    encode_parts, gradient_queue, intermediate_queue, reply_queue,
    RPC_QUEUE,
)
from split_learning_tpu.runtime.spans import make_tracer, unpack_ctx
from split_learning_tpu.runtime.validation import dataset_for_model

def _wire_np_dtype(name: str):
    from split_learning_tpu.config import TransportConfig
    name = TransportConfig.WIRE_DTYPE_ALIASES.get(name, name)
    if name == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.dtype(name)


def device_wire_dtype(wire_np_dtype):
    """jnp dtype for the ON-DEVICE wire cast, or None when no device
    cast applies: fp32 ships as-is, and int8 quantizes host-side
    (QuantLeaf needs the absmax, which would be a device sync)."""
    dt = np.dtype(wire_np_dtype)
    if dt == np.dtype(np.float16):
        return jnp.float16
    if dt.name == "bfloat16":
        return jnp.bfloat16
    return None


def _cast_for_wire(tree, dtype):
    """Cast float leaves to the wire dtype ON DEVICE, before the
    device->host fetch: the async sender's ``np.asarray`` then moves
    wire-width bytes instead of fp32 (half the PCIe traffic on the
    bf16 default), and the host-side ``_to_wire_tree`` cast becomes a
    no-op.  Bit-identical to casting on host — both round to nearest
    even.  No-op when ``dtype`` is None."""
    if dtype is None:
        return tree

    def conv(leaf):
        ldt = getattr(leaf, "dtype", None)
        if (ldt is None or ldt == jax.dtypes.float0
                or not jnp.issubdtype(ldt, jnp.floating)):
            return leaf
        return leaf if ldt == dtype else leaf.astype(dtype)
    return jax.tree_util.tree_map(conv, tree)


def _start_host_copy(tree) -> None:
    """Kick off the device→host transfer of every leaf WITHOUT blocking
    (jax.Array.copy_to_host_async), so by the time the async sender's
    encode thunk calls np.asarray the bytes are already on host — the
    transfer overlaps the training thread's next microbatch compute."""
    for leaf in jax.tree_util.tree_leaves(tree):
        copy = getattr(leaf, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:  # noqa: BLE001 — purely a prefetch hint
                return


def _quant_int8(a: np.ndarray):
    """Absmax int8 quantization of one float payload leaf.

    A non-finite payload ships raw fp32 instead: quantizing NaN/inf is
    undefined, and the diverged values must survive the hop so the
    receiver's NaN sentinel (``src/train/VGG16.py:169-171``) fires."""
    a32 = np.asarray(a, np.float32)
    amax = float(np.max(np.abs(a32))) if a32.size else 0.0
    if not np.isfinite(amax):
        return a32
    scale = (amax / 127.0) or 1.0   # all-zero payload: any scale works
    return QuantLeaf(q=np.round(a32 / scale).astype(np.int8),
                     scale=scale)


def _to_wire_tree(tree, dtype=np.float32):
    """Device pytree -> numpy payload for Activation/Gradient messages.

    Stage boundaries may be pytrees (e.g. BERT's (hidden, mask),
    models/bert.py): float leaves travel as ``dtype``
    (``transport.wire-dtype``; fp16/bf16 halve the hop bytes vs the
    reference's fp32 pickles, int8 absmax-quantizes for ~4x), bool/int
    leaves keep their dtype, and float0 gradient leaves (cotangents of
    non-differentiable inputs) become zeros so they pickle."""
    quantize = dtype == np.int8

    def conv(leaf):
        if getattr(leaf, "dtype", None) == jax.dtypes.float0:
            return np.zeros(np.shape(leaf),
                            np.float32 if quantize else dtype)
        a = np.asarray(leaf)
        # jnp.issubdtype, NOT np.issubdtype: numpy's lattice does not
        # classify ml_dtypes (bfloat16 model activations) as floating,
        # which would silently skip the wire cast
        if jnp.issubdtype(a.dtype, jnp.floating):
            return _quant_int8(a) if quantize else a.astype(dtype,
                                                            copy=False)
        return a
    return jax.tree_util.tree_map(conv, tree)


def _from_wire_tree(tree):
    """Wire payload tree -> device arrays.  Self-describing: QuantLeaf
    (legacy per-tensor OR tiled codec form) and SparseLeaf decode
    without knowing the sender's codec config, so mixed-policy
    deployments interoperate."""
    def conv(leaf):
        if isinstance(leaf, QuantLeaf):
            from split_learning_tpu.runtime.codec.quant import (
                dequantize_leaf,
            )
            return dequantize_leaf(leaf)
        if isinstance(leaf, SparseLeaf):
            from split_learning_tpu.runtime.codec.sparse import (
                densify_leaf,
            )
            return densify_leaf(leaf)
        return jnp.asarray(leaf)
    return jax.tree_util.tree_map(conv, tree)


def _wire_vdot(out_tree, ct_tree):
    """<out, cotangent> over the float leaves of a boundary pytree (the
    scalar whose gradient backpropagates a received cotangent)."""
    tot = jnp.zeros((), jnp.float32)
    for o, c in zip(jax.tree_util.tree_leaves(out_tree),
                    jax.tree_util.tree_leaves(ct_tree)):
        if jnp.issubdtype(o.dtype, jnp.floating):
            tot = tot + jnp.vdot(o.astype(jnp.float32),
                                 c.astype(jnp.float32))
    return tot


#: async drain-on-pause idle grace (seconds): a PAUSEd async consumer
#: keeps eating its in-flight activation stream until every origin
#: feeder's final epoch fence arrived, or the queue has been silent
#: this long — the bounded-staleness tax a delayed stream may cost
#: (frames beyond the grace are dropped, not waited for)
ASYNC_DRAIN_IDLE_S = 0.5


@dataclasses.dataclass
class _AbortPause(Pause):
    """Local sentinel: the round was abandoned (STOP/fresh START arrived
    mid-loop) — unwind WITHOUT publishing any UPDATE.  Distinct from a
    server Pause(send_weights=False), which still expects a weight-less
    UPDATE (FLEX non-aggregation rounds)."""


def make_optimizer_from_dict(learning: dict | None) -> tuple[
        optax.GradientTransformation, LearningConfig]:
    d = dict(learning or {})
    known = {f.name for f in dataclasses.fields(LearningConfig)}
    cfg = LearningConfig(**{k: v for k, v in d.items() if k in known})
    from split_learning_tpu.runtime.context import make_optimizer
    return make_optimizer(cfg), cfg


def _ops_cache_key(model_key, start_layer, end_layer, learning,
                   model_kwargs) -> tuple:
    d = dict(learning or {})
    # loop-behavior-only knobs: the jitted ops are identical with the
    # flag on or off, so sharing the compiled bundle across the A/B is
    # free (and keeps the sync-overlap bench/test legs compile-warm)
    d.pop("sync_overlap", None)
    return (model_key, start_layer, end_layer,
            repr(sorted(d.items())),
            repr(sorted((model_kwargs or {}).items())))


#: jitted-op bundles shared across ShardRunner instances with identical
#: (model, layer range, learning, kwargs) — see runtime/memo.py
_OPS_CACHE: dict = {}
_OPS_CACHE_MAX = 64


class ShardRunner:
    """Jitted forward / recompute-backward / optimizer ops for one shard.

    Parameters are carried as ``(frozen, trainable)``: ``trainable`` is
    ``{"lora": adapters, "head": unfrozen params}``.  Without LoRA the
    whole shard rides in ``head`` and ``frozen``/``lora`` are empty, so
    plain training and adapter training share one code path.  With
    ``learning.lora_rank > 0`` this reproduces the reference's peft wrap:
    adapters on attention kernels, base frozen, classifier head unfrozen
    on the final shard (``src/RpcClient.py:61-66``, ``:99-103``).
    """

    def __init__(self, model_key: str, start_layer: int, end_layer: int,
                 learning: dict | None, model_kwargs: dict | None = None,
                 seed: int = 0):
        self.model = build_model(model_key, start_layer=start_layer,
                                 end_layer=end_layer,
                                 **(model_kwargs or {}))
        self.start_layer = start_layer
        self.learning_dict = dict(learning or {})  # for change detection
        self.optimizer, self.learning = make_optimizer_from_dict(learning)
        self.rng = jax.random.key(seed)
        self._counter = 0
        lrn = self.learning
        self.lora_rank, self.lora_alpha = lrn.lora_rank, lrn.lora_alpha
        # async decoupled mode (learning.mode: async): every non-final
        # stage trains against a local auxiliary head on its cut
        # boundary (ops/auxiliary.py) instead of waiting for a wire
        # cotangent.  The module is deterministic from the cache key
        # (model_key fixes the label space, learning fixes the
        # architecture), so sharing it through _OPS_CACHE is safe.
        self.aux = None
        if lrn.mode == "async":
            from split_learning_tpu.ops.auxiliary import (
                build_aux_head, num_classes_for,
            )
            self.aux = build_aux_head(lrn.aux_head,
                                      num_classes_for(model_key),
                                      hidden=lrn.aux_hidden)

        cache_key = _ops_cache_key(model_key, start_layer, end_layer,
                                   learning, model_kwargs)
        ops = bounded_setdefault(_OPS_CACHE, _OPS_CACHE_MAX, cache_key,
                                 self._build_ops)
        (self.fwd, self.bwd, self.last_step, self.whole_step,
         self.aux_step, self.apply_update, self._merged) = ops

    def init_aux_params(self, boundary_shapes) -> dict:
        """Aux-head params for this shard's boundary shape pytree (the
        ``jax.eval_shape`` of ``fwd``)."""
        from split_learning_tpu.ops.auxiliary import init_aux_params
        return init_aux_params(self.aux, self.next_rng(),
                               boundary_shapes)

    def _build_ops(self) -> tuple:
        """The five jitted ops + merged-params helper.  Closes over the
        (stateless) model/optimizer only — everything instance-specific
        (rng stream, params, stats) is passed per call, which is what
        makes the bundle shareable through ``_OPS_CACHE``."""

        def merged(frozen, t):
            base = {**frozen, **t["head"]}
            if not t["lora"]:
                return base
            return lora_merge(base, t["lora"], alpha=self.lora_alpha,
                              rank=self.lora_rank)

        def _variables(params, stats):
            v = {"params": params}
            if stats:
                v["batch_stats"] = stats
            return v

        @jax.jit
        def fwd(frozen, t, stats, x, rng):
            """Forward in train mode; batch_stats update deferred to the
            backward recompute (single update per consumed batch)."""
            out, _ = self.model.apply(
                _variables(merged(frozen, t), stats), x, train=True,
                mutable=["batch_stats"], rngs={"dropout": rng})
            return out

        @jax.jit
        def bwd(frozen, t, stats, x, ct, rng):
            """Recompute forward, backprop the received cotangent.

            Returns (trainable_grads, input_grad, new_stats)."""
            def f(tt, xx):
                out, mut = self.model.apply(
                    _variables(merged(frozen, tt), stats), xx, train=True,
                    mutable=["batch_stats"], rngs={"dropout": rng})
                return _wire_vdot(out, ct), mut
            # allow_int: stage-1 inputs can be integer token ids; their
            # float0 cotangent is never used (no upstream hop to route to)
            grad_fn = jax.grad(f, argnums=(0, 1), has_aux=True,
                               allow_int=True)
            (gt, gx), mut = grad_fn(t, x)
            new_stats = dict(stats)
            new_stats.update(mut.get("batch_stats", {}))
            return gt, gx, new_stats

        @jax.jit
        def last_step(frozen, t, stats, x, labels, rng):
            """Last stage: CE loss, grads wrt trainables AND input.

            Returns (loss, trainable_grads, input_grad, new_stats)."""
            def f(tt, xx):
                out, mut = self.model.apply(
                    _variables(merged(frozen, tt), stats), xx, train=True,
                    mutable=["batch_stats"], rngs={"dropout": rng})
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    out.astype(jnp.float32), labels).mean()
                return loss, mut
            (loss, mut), (gt, gx) = jax.value_and_grad(
                f, argnums=(0, 1), has_aux=True, allow_int=True)(t, x)
            new_stats = dict(stats)
            new_stats.update(mut.get("batch_stats", {}))
            return loss, gt, gx, new_stats

        @jax.jit
        def whole_step(frozen, t, stats, x, labels, rng):
            """Degenerate whole-model client (``layers == [0, 0]``,
            ``src/Server.py:241-243``): plain local train step."""
            def f(tt):
                out, mut = self.model.apply(
                    _variables(merged(frozen, tt), stats), x, train=True,
                    mutable=["batch_stats"], rngs={"dropout": rng})
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    out.astype(jnp.float32), labels).mean()
                return loss, mut
            (loss, mut), gt = jax.value_and_grad(f, has_aux=True)(t)
            new_stats = dict(stats)
            new_stats.update(mut.get("batch_stats", {}))
            return loss, gt, new_stats

        @jax.jit
        def apply_update(t, opt_state, grads):
            updates, new_opt = self.optimizer.update(grads, opt_state, t)
            return optax.apply_updates(t, updates), new_opt

        aux_step = None
        if self.aux is not None:
            @jax.jit
            def aux_step(frozen, t, aux_p, stats, x, labels, rng):
                """Decoupled forward + local aux loss in ONE program:
                the stage steps on its auxiliary gradient immediately
                after the forward tick — no wire cotangent, no
                gradient_queue park.  Returns the boundary output so
                the activation still streams downstream.

                Returns (loss, out, shard_grads, aux_grads,
                new_stats)."""
                def f(tt, ap):
                    out, mut = self.model.apply(
                        _variables(merged(frozen, tt), stats), x,
                        train=True, mutable=["batch_stats"],
                        rngs={"dropout": rng})
                    logits = self.aux.apply({"params": ap}, out)
                    loss = \
                        optax.softmax_cross_entropy_with_integer_labels(
                            logits.astype(jnp.float32), labels).mean()
                    return loss, (out, mut)
                (loss, (out, mut)), (gt, ga) = jax.value_and_grad(
                    f, argnums=(0, 1), has_aux=True)(t, aux_p)
                new_stats = dict(stats)
                new_stats.update(mut.get("batch_stats", {}))
                return loss, out, gt, ga, new_stats

        return (fwd, bwd, last_step, whole_step, aux_step,
                apply_update, jax.jit(merged))

    def partition_params(self, params, is_final_shard: bool):
        """(frozen, trainable) split of the shard's params.

        LoRA off: everything trainable.  LoRA on: adapters over target
        kernels; the model's final layer (classifier) is unfrozen when
        this shard holds it."""
        self.lora_noop = False
        if self.lora_rank <= 0:
            return {}, {"lora": {}, "head": params}
        unfrozen_names = []
        if is_final_shard:
            unfrozen_names = [self.model.specs[-1].name]
        frozen, head = split_frozen(params, unfrozen_names)
        adapters = lora_init(self.next_rng(), frozen,
                             targets=self.learning.lora_targets,
                             rank=self.lora_rank)
        if not adapters and not head:
            # no target kernels in this shard (conv-only model/slice):
            # freezing everything would silently train nothing — fall
            # back to full training and let the caller warn
            self.lora_noop = True
            return {}, {"lora": {}, "head": params}
        return frozen, {"lora": adapters, "head": head}

    def merge_params(self, frozen, t):
        """Bake adapters back into dense weights (merge_and_unload,
        ``src/RpcClient.py:121-122``) for UPDATE/aggregation."""
        return self._merged(frozen, t)

    def next_rng(self):
        self._counter += 1
        return jax.random.fold_in(self.rng, self._counter)


@dataclasses.dataclass
class _Inflight:
    x: Any
    rng: Any
    trace: list
    labels: Any = None
    n: int = 0   # batch size; counted into num_samples at BACKWARD time


class ProtocolClient:
    """One split-learning client process (reference ``client.py`` +
    ``src/RpcClient.py``)."""

    def __init__(self, cfg: Config, client_id: str, stage: int,
                 transport: Transport | None = None,
                 cluster: int | None = None, profile: dict | None = None,
                 logger: Logger | None = None):
        self.cfg = cfg
        self.client_id = client_id
        self.stage = stage
        self.cluster = cluster
        self.profile = profile
        if transport is None:
            # configured stack: base bus -> chaos injection -> reliable
            # delivery (tests pass a pre-built transport instead)
            from split_learning_tpu.runtime.chaos import (
                make_runtime_transport,
            )
            transport = make_runtime_transport(cfg, client_id)
        self.bus = transport
        from split_learning_tpu.runtime.trace import (
            HistogramSet, default_fault_counters, default_wire_counters,
        )
        self.faults = getattr(self.bus, "faults", None) \
            or default_fault_counters
        self.wire = getattr(self.bus, "wire", None) \
            or default_wire_counters
        # distributed-tracing surface: the transport stack's tracer
        # when make_runtime_transport built one, else this client's own
        self.tracer = getattr(self.bus, "tracer", None) \
            or make_tracer(cfg, client_id)
        self.hists = getattr(self.bus, "hists", None) or HistogramSet()
        # chunked-frame reassembly is per consumer thread; the client is
        # single-threaded over its queues
        self._assembler = FrameAssembler()
        self._chunk_bytes = cfg.transport.chunk_mb << 20
        self.log = logger or Logger.for_run(cfg, client_id,
                                            console=False)
        # live telemetry plane (runtime/telemetry.py): gauges +
        # background heartbeat emitter publishing a TelemetrySnapshot
        # (counters, gauges, histogram digests, EWMA samples/s) on the
        # rpc queue every observability.heartbeat-interval seconds —
        # started at the first START, so the server's FleetMonitor
        # hears this client even through a long first-round compile
        from split_learning_tpu.runtime.telemetry import (
            GaugeSet, TelemetryEmitter,
        )
        self.gauges = GaugeSet()
        obs = getattr(cfg, "observability", None)
        self.telemetry = TelemetryEmitter(
            client_id, self._send_heartbeat,
            interval=(obs.heartbeat_interval if obs is not None else 0),
            faults=self.faults, wire=self.wire, hists=self.hists,
            gauges=self.gauges,
            samples_fn=lambda: self.num_samples, stage=stage)
        # hierarchical heartbeat roll-up: where heartbeats publish —
        # a digest queue (START extra.digest named this client's
        # aggregator node) or None for direct rpc beats; a mid-round
        # DigestRoute frame re-points it (digest-node death fallback)
        self._hb_queue: str | None = None
        # compute performance-attribution plane (runtime/perf.py):
        # sampled step timing (device fence only every
        # perf.sample-every steps), compile/retrace accounting on the
        # runner's jitted ops, HBM watermarks, MFU — emitted as one
        # kind=perf record per round and ridden on heartbeats as gauges
        from split_learning_tpu.runtime.perf import (
            make_perf_plane, process_capture,
        )
        # process_capture() is non-None only when this client shares
        # the server's process (in-proc cells): its hot-loop ticks then
        # close a POST /profile steps=K window after K steps.  Separate
        # client processes get None — the round boundary closes the
        # window there (it profiles the server process).
        self.perf = make_perf_plane(
            cfg, client_id, gauges=self.gauges, hists=self.hists,
            faults=self.faults, tracer=self.tracer, log=self.log,
            capture=process_capture())
        self.runner: ShardRunner | None = None
        self.frozen: dict = {}
        self.trainable: dict = {}
        self.stats: dict = {}
        self.opt_state = None
        self.loader = None
        self.epochs = 1
        self.sda_size = 1
        self.sda_strict = False
        self.sda_feeders = None
        self.sda_fence_quorum = 1
        self.round_ok = True
        self.num_samples = 0
        self.wire_dtype = _wire_np_dtype(cfg.transport.wire_dtype)
        self._dev_cast = device_wire_dtype(self.wire_dtype)
        # per-queue-family wire codecs (transport.codec): quantized
        # activations, EF-sparsified gradients, delta-encoded Updates.
        # Families without a policy fall back to the wire-dtype path.
        self.codecs = make_codecs(cfg, faults=self.faults)
        # scheduler-granted knob retune currently applied (START
        # extra.sched, runtime/scheduler.py): the codec-override map
        # in force, so a repeated grant doesn't rebuild codecs (and
        # reset their EF state) every round
        self._sched_codec_over: dict | None = None
        # delta codec state: (version, base tree) of the last START
        # params, and the shadow version the server advertised — a
        # delta is sent ONLY when these agree (else: full frame)
        self._delta_base = None
        self._delta_advert = None
        self._agg_group = None   # L1 group index (aggregation.fan-in)
        # async decoupled mode (learning.mode: async): client-local
        # auxiliary-head state — params + their own optimizer stream,
        # lazily shaped from the first batch's boundary eval_shape and
        # reset whenever a re-plan moves the cut (the shape signature
        # below is the reset trigger)
        self.aux_params = None
        self.aux_opt_state = None
        self._aux_sig = None
        # pipelined rounds: samples trained by overlap ticks between
        # the round's UPDATE and the next START (counted into the NEXT
        # round's Update), and control frames an overlap loop popped
        # off the reply queue for run() to handle in order
        self._overlap_samples = 0
        self._pending_ctrl: list[bytes] = []
        # sync-mode round-boundary overlap (learning.sync-overlap):
        # the speculative cache built between this round's UPDATE and
        # the next START (_sync_overlap_ticks), the splice the next
        # round's hot loop consumes when the speculation held, and the
        # last START's shape (the hold/re-seed predictor)
        self._sync_cache: dict | None = None
        self._spliced: dict | None = None
        self._last_start_held = False
        self._update_pub_t = 0.0
        if cfg.checkpoint.load:
            self._load_ef_state()
        # device-resident NaN sentinel: hot loops fold jnp.isfinite
        # into this WITHOUT a host sync; _send_update reads it once
        # per round (slcheck JX001)
        self._ok_dev = None

    # -- control plane -----------------------------------------------------

    def _decode(self, raw: bytes, queue: str | None = None):
        """Tolerant decode: a frame that fails a checksum (or ANY guard
        inside decode — a crafted pickle can raise arbitrary exceptions
        from numpy reconstruction) is dropped and counted, never fatal:
        a flipped bit on the wire must cost one message (which the
        reliable layer redelivers), not the process.  Same breadth as
        the server's rpc pump.  Returns None both for dropped frames
        and for a chunk of a still-partial message.

        A decoded message carrying a wire trace context becomes a
        *consume* span parented to the sender's publish span (the
        cross-participant flow edge), and its context send-time feeds
        the ``frame_rtt`` histogram."""
        t_wall = time.time()
        t0 = time.perf_counter()
        try:
            msg = self._assembler.feed(raw)
        except Exception as e:  # noqa: BLE001 — see docstring
            self.faults.inc("corrupt_rejected")
            self.log.warning(f"dropping undecodable frame: {e}")
            self.wire.add_decode(time.perf_counter() - t0)
            return None
        dt = time.perf_counter() - t0
        self.wire.add_decode(dt)
        self.hists.observe("decode", dt)
        if msg is not None:
            ctx = unpack_ctx(getattr(msg, "_ctx", None))
            if ctx is not None:
                _, sender_span, t_send = ctx
                rtt = max(0.0, t_wall - t_send)
                self.hists.observe("frame_rtt", rtt)
                self.tracer.record(
                    "consume", t_wall, t_wall + dt, parent=sender_span,
                    queue=queue, kind=type(msg).__name__,
                    nbytes=len(raw), rtt_ms=round(rtt * 1e3, 3),
                    round=getattr(msg, "round_idx", None))
        if isinstance(msg, BlackboxDump):
            # fleet-snapshot request: absorbed HERE, in the one decode
            # path every reply-queue consumer shares, so the dump fires
            # whatever phase the client is in (idle pump, PAUSE wait,
            # barrier) and no state machine sees an unexpected frame
            blackbox.record("dump_request", reason=msg.reason)
            blackbox.dump(msg.reason or "fleet_snapshot")
            return None
        return msg

    def _publish_parts(self, queue: str, build, kind: str | None = None
                       ) -> None:
        """Data-plane publish: ``build(ctx)`` produces the frame part
        list (device fetch + TENSOR encode + chunking) carrying the
        wire trace context ``ctx``.  On an async bus the thunk is
        enqueued and runs on the background sender — microbatch k's
        transfer/encode/socket-write overlaps microbatch k+1's compute;
        on a plain bus it runs inline.  The *publish* span opens at
        enqueue (queue-time included) and closes when the frame bytes
        exist; its id rides ``ctx`` to the receiver's consume span."""
        span = self.tracer.start("publish", always=False, queue=queue,
                                 kind=kind,
                                 round=getattr(self, "round_idx", None))
        ctx = self.tracer.wire_context(span)
        if getattr(self.bus, "deferred", False):
            def thunk():
                parts = build(ctx)
                span.end(nbytes=sum(len(p) for p in parts))
                return parts
            self.bus.publish(queue, thunk)
            return
        t0 = time.perf_counter()
        parts = build(ctx)
        dt = time.perf_counter() - t0
        self.wire.add_encode(dt)
        self.hists.observe("encode", dt)
        span.end(nbytes=sum(len(p) for p in parts))
        for part in parts:
            self.bus.publish(queue, part)
            self.wire.count_out(queue, len(part))

    # -- wire codec plumbing -----------------------------------------------

    def _wire_out(self, tree, family: str, queue: str):
        """Device-side wire stage, ON the training thread (so stateful
        codecs advance in publish order): codec ``prepare`` when a
        policy covers ``family``, else the plain device wire cast.
        Counts the pre-codec dense-equivalent bytes so the compression
        ratio is measured, not estimated."""
        c = self.codecs.get(family)
        if c is None:
            return _cast_for_wire(tree, self._dev_cast)
        self.wire.count_raw(queue,
                            wire_raw_nbytes(tree, self.wire_dtype))
        return c.prepare(tree, key=queue)

    def _wire_host(self, tree, family: str):
        """Host-side wire stage (runs inside the publish thunk, i.e.
        on the async sender): codec ``encode`` or the plain host cast."""
        c = self.codecs.get(family)
        if c is None:
            return _to_wire_tree(tree, self.wire_dtype)
        return c.encode(tree)

    def _encode_update_wire(self, params_h):
        """(wire params tree, delta_base version) for this round's
        UPDATE: a quantized delta against the START base when the
        version chain is intact, else the full fp32 frame (the resync
        path — restarted client, moved shadow, no rpc codec)."""
        rpc = self.codecs.get("rpc")
        if rpc is None or params_h is None:
            return params_h, None
        base = self._delta_base
        if (base is None or self._delta_advert is None
                or base[0] != self._delta_advert):
            return params_h, None   # server counts the full frame
        ver, base_tree = base
        self.wire.count_raw(
            RPC_QUEUE, wire_raw_nbytes(params_h, np.float32))
        return rpc.encode_update(params_h, base_tree), ver

    def _apply_sched_knobs(self, knobs: dict | None) -> None:
        """Apply a scheduler-granted per-client retune (START
        ``extra.sched``): a codec-override map is merged over the
        config's ``transport.codec`` block and the wire codecs are
        rebuilt.  Idempotent — the same grant repeated every round
        rebuilds nothing (EF-stateful codecs keep their residuals);
        a revoked grant (None) reverts to the config codecs.  A bad
        spec is rejected-and-counted, never fatal: a scheduler bug
        must cost one knob frame, not the client."""
        over = (knobs or {}).get("codec") or None
        if over == self._sched_codec_over:
            return
        import types

        from split_learning_tpu.runtime.codec.specs import (
            CodecSpecError,
        )
        base = dict(getattr(self.cfg.transport, "codec", None) or {})
        merged = {**base, **(over or {})}
        shim = types.SimpleNamespace(transport=types.SimpleNamespace(
            codec=merged or None))
        try:
            codecs = make_codecs(shim, faults=self.faults)
        except CodecSpecError as e:
            self.faults.inc("sched_knob_rejects")
            self.log.warning(
                f"rejecting scheduler codec knob {over!r}: {e}")
            return
        self.codecs = codecs
        self._sched_codec_over = over
        self.log.info(
            "scheduler retune: codec "
            + (f"override {over}" if over else "reverted to config"),
            "cyan")

    def _ef_stateful_codecs(self):
        for family in ("gradient", "rpc"):
            c = self.codecs.get(family)
            if c is not None and hasattr(c, "state_dict"):
                yield family, c

    def _save_ef_state(self):
        """Persist each stateful codec's error-feedback residuals next
        to the model checkpoint (atomic sidecar) so a restarted client
        resumes with its unsent gradient mass instead of dropping it."""
        from split_learning_tpu.runtime.checkpoint import (
            save_sidecar_arrays,
        )
        for family, c in self._ef_stateful_codecs():
            state = c.state_dict()
            if state:
                save_sidecar_arrays(
                    self.cfg.checkpoint.directory,
                    f"ef_{self.client_id}_{family}", state)

    def _load_ef_state(self):
        from split_learning_tpu.runtime.checkpoint import (
            load_sidecar_arrays,
        )
        for family, c in self._ef_stateful_codecs():
            state = load_sidecar_arrays(
                self.cfg.checkpoint.directory,
                f"ef_{self.client_id}_{family}")
            if state:
                c.load_state_dict(state)

    def register(self):
        self.bus.publish(RPC_QUEUE, encode(Register(
            client_id=self.client_id, stage=self.stage,
            cluster=self.cluster, profile=self.profile)))
        self.log.info(f"[>>>] REGISTER stage={self.stage}")

    def _send_heartbeat(self, snapshot: dict) -> None:
        """Publish one HEARTBEAT (called by the emitter's background
        thread): liveness + the full telemetry snapshot, on the rpc
        queue — or on this client's assigned digest queue when the
        server routed its beats through an aggregator node's roll-up
        (``observability.digest-interval``).  Not logged — at one
        frame per interval per client the [>>>] markers would drown
        the protocol trace."""
        # allow-send: the target alternates between the rpc queue and
        # this client's assigned digest queue — both legal for
        # (client, Heartbeat) in the model, unresolvable statically
        self.bus.publish(self._hb_queue or RPC_QUEUE, encode(Heartbeat(  # slcheck: allow-send
            client_id=self.client_id,
            round_idx=getattr(self, "round_idx", 0),
            telemetry=snapshot)))

    def run(self):
        """Lifecycle loop + telemetry guard: however the loop exits —
        STOP, closed transport, or a fault unwinding a hot loop (e.g.
        a scripted ChaosCrash) — the heartbeat thread must die with
        it, or a 'crashed' client would keep reporting healthy."""
        try:
            return self._run()
        finally:
            self.telemetry.stop()

    def _run(self):
        """Blocking lifecycle loop; returns on STOP.

        Until the first START arrives, REGISTER is re-sent every few
        seconds: a client that comes up before the server would otherwise
        lose its registration to the server's startup queue purge
        (``src/Utils.py:8-32`` hygiene — the reference simply requires
        clients to start after the server, README.md:144-171)."""
        from split_learning_tpu.runtime.bus import QueueClosed
        self.register()
        q = reply_queue(self.client_id)
        started = False
        while True:
            try:
                # control frames an async overlap loop already popped
                # from the reply queue come first — same order they
                # arrived on the wire
                if self._pending_ctrl:
                    raw = self._pending_ctrl.pop(0)
                else:
                    raw = self.bus.get(q,
                                       timeout=None if started else 3.0)
            except (QueueClosed, ConnectionError, OSError) as e:
                # Transport gone while idle BETWEEN rounds: after at
                # least one START this is almost always the STOP fan-out
                # racing the broker teardown (server exits right after
                # publishing it) — exit cleanly instead of dying with a
                # traceback.  During registration (no START yet) a dead
                # transport is a real deployment failure: stay loud so
                # the operator sees more than a server-side timeout.
                # Mid-round transport loss surfaces inside the hot loops
                # and still raises.
                if not started:
                    raise
                self.log.warning(f"transport closed ({e}); shutting down")
                self.tracer.close()
                return
            if raw is None:
                if not started:
                    self.register()
                continue
            msg = self._decode(raw, q)
            if msg is None:
                continue
            if isinstance(msg, Start):
                started = True
                # heartbeats begin at the first START (idempotent):
                # the FleetMonitor must hear this client through the
                # shard build + first-round compile that follow
                self.telemetry.start()
                self._on_start(msg)
                self.bus.publish(RPC_QUEUE, encode(Ready(
                    client_id=self.client_id, round_idx=self.fence)))
                self.log.info("[>>>] READY")
            elif isinstance(msg, Syn):
                self._on_syn(msg)
            elif isinstance(msg, DigestRoute):
                # mid-round heartbeat re-route (digest-node death
                # fallback): adopt the new target and beat once NOW so
                # the server's liveness view never gaps
                self._hb_queue = msg.queue
                try:
                    self.telemetry.beat_once()
                except Exception:  # noqa: BLE001 — transport teardown
                    pass           # races the re-route; next beat covers
            elif isinstance(msg, Stop):
                self.log.info(f"[<<<] STOP {msg.reason}")
                # drain the async sender before the process exits: a
                # still-enqueued frame must not die with this client
                flush = getattr(self.bus, "flush", None)
                if flush is not None:
                    flush(timeout=30.0)
                self.tracer.close()
                return
            else:
                self.log.warning(f"unexpected control message {msg}")

    def _on_start(self, msg: Start):
        self.log.info(f"[<<<] START layers=[{msg.start_layer}, "
                      f"{msg.end_layer}] cluster={msg.cluster}")
        self.cluster = msg.cluster
        extra = msg.extra or {}
        # join the server's run-scoped trace: every span this client
        # journals (and every wire context it sends) now carries the
        # same trace id, across processes
        if extra.get("trace_id"):
            self.tracer.adopt_trace_id(extra["trace_id"])
        self.epochs = int(extra.get("epochs", 1))
        self.sda_size = int(extra.get("sda_size", 1))
        self.round_idx = msg.round_idx
        # delta-codec version chain: the server advertises the shadow
        # version it holds for us; _send_update sends a delta only when
        # our local base carries the same tag (else: full-frame resync)
        self._delta_advert = extra.get("delta_base_version")
        # aggregator tree (aggregation.fan-in): the round UPDATE goes
        # to this L1 group's aggregate queue instead of rpc_queue
        # (None = direct-to-root; re-read every START, the tree can
        # re-shape per round).  Tree rounds never advertise a delta
        # base, so the full-frame path follows automatically.
        self._agg_group = extra.get("agg_group")
        # scheduler-granted per-client knob retune (heavier wire codec
        # for a wire-slow straggler; runtime/scheduler.py)
        self._apply_sched_knobs(extra.get("sched"))
        # hierarchical heartbeat roll-up: beats publish to this digest
        # queue (an aggregator node folds them into FleetDigest
        # frames); None = direct rpc heartbeats.  Re-read every START
        # — the route can move with the node topology.
        self._hb_queue = extra.get("digest")
        # server-issued per-invocation generation: stamps every message
        # this client sends so the server/peers can drop strays from an
        # invocation that was already abandoned (round_idx alone can't —
        # sequential strategies reuse it across sub-calls)
        self.fence = int(extra.get("gen", msg.round_idx))
        self.n_stages = int(extra.get("n_stages", self.cfg.num_stages))
        # 2LS fixed edge<->head pairing: route this client's forward
        # data plane through its pair-indexed queue (None = shared)
        self.pair = extra.get("pair")
        # DCSL dispatch topology: next-stage client ids whose per-device
        # queues this client scatters successive batches across,
        # round-robin (other/DCSL/src/Scheduler.py:21-26, :110-133)
        self.sda_peers = extra.get("sda_peers")
        self.sda_fence_quorum = int(extra.get("sda_fence_quorum", 1))
        self.sda_strict = bool(extra.get("sda_strict", False))
        self.sda_feeders = extra.get("sda_feeders")
        # sync-overlap speculation (built between the last UPDATE and
        # this START): consumed below iff it matches the round this
        # START actually opens; every mismatch discards with state
        # restored so the round stays bit-identical to non-overlapped
        sc, self._sync_cache = self._sync_cache, None
        if self._spliced is not None:
            # a previous START spliced but no round ever consumed it
            # (e.g. an elastic re-plan fanned out a second START before
            # SYN): unwind the speculation's state — the rng counter on
            # a kept runner, the kept loader's shuffle (hold mode), or
            # the adopted clone's shuffle (reseed mode) — exactly as a
            # discard would, or the bit-identity contract breaks
            stale, self._spliced = self._spliced, None
            if (stale["mode"] == "reseed"
                    and self.loader is stale["loader"]
                    and stale["loader_rng0"] is not None):
                self.loader._rng.bit_generator.state = \
                    stale["loader_rng0"]
                self.faults.inc("overlap_discards")
            else:
                self._discard_sync_cache(stale, runner_kept=True,
                                         loader_kept=True)
        self._last_start_held = msg.params is None
        if msg.params is None:
            # FLEX non-reseed round (other/FLEX/src/Server.py:220-226):
            # START without weights — keep the locally persisted shard
            # (and its optimizer state) from the previous round
            if (getattr(self, "runner", None) is None
                    or self.runner.start_layer != msg.start_layer):
                raise RuntimeError(
                    "START without params but no matching local shard "
                    f"(layers [{msg.start_layer}, {msg.end_layer}])")
            runner_kept = True
            if dict(msg.learning or {}) != self.runner.learning_dict:
                # hyperparams changed mid-hold (e.g. lr decay): rebuild
                # the jitted ops around the kept weights; optimizer
                # state resets, matching the reference's fresh-optimizer-
                # per-round behavior (src/train/VGG16.py:62)
                self.runner = ShardRunner(
                    self.cfg.model_key, msg.start_layer, msg.end_layer,
                    msg.learning,
                    model_kwargs=dict(self.cfg.model_kwargs or {}),
                    seed=self.cfg.seed
                    + zlib.crc32(self.client_id.encode()) % 100000)
                self.perf.wrap_runner(self.runner)
                self.opt_state = self.runner.optimizer.init(self.trainable)
                self._reset_aux()
                runner_kept = False
                self.log.info("hyperparams changed: rebuilt runner "
                              "(weights kept)")
            else:
                self.log.info("keeping local shard weights (no re-seed)")
            # rebuild the loader on a hold START when (a) refresh
            # re-samples every round (the reference rebuilds its loader
            # on every START when refresh is on, src/RpcClient.py:108)
            # or (b) an elastic re-plan moved this client's data
            # distribution without moving its layer range — otherwise
            # the server's plan and the trained subset silently diverge
            loader_kept = True
            if (self.stage == 1 and msg.label_counts is not None
                    and ((msg.extra or {}).get("refresh")
                         or [int(c) for c in msg.label_counts]
                         != getattr(self, "_loader_counts", None))):
                self._build_loader(msg)
                loader_kept = False
            # hold START: the delta base survives only while it still
            # matches the server's shadow — a drifted advertisement
            # (shadow lost/moved) breaks the chain, so fall back to a
            # full-frame UPDATE rather than a delta nobody can fold
            if (self._delta_base is not None
                    and self._delta_base[0] != self._delta_advert):
                self._delta_base = None
            if sc is not None:
                # the speculation holds iff the round it predicted is
                # the round it got: a hold START with the SAME runner
                # (same params AND rng stream) and the SAME loader —
                # then the cached forwards are bit-exactly the round's
                # first microbatches and the hot loop consumes them
                if (sc["mode"] == "hold" and runner_kept
                        and loader_kept):
                    self._spliced = sc
                    self.faults.inc("overlap_splices")
                    self.log.info(
                        f"sync overlap: splicing {len(sc['items'])} "
                        "precomputed forward(s) into this round")
                else:
                    self._discard_sync_cache(sc, runner_kept,
                                             loader_kept)
            return
        model_kwargs = dict(self.cfg.model_kwargs or {})
        self.runner = ShardRunner(
            self.cfg.model_key, msg.start_layer, msg.end_layer,
            msg.learning, model_kwargs=model_kwargs,
            seed=self.cfg.seed
            + zlib.crc32(self.client_id.encode()) % 100000)
        # compile/retrace accounting on the five jitted ops (instance
        # attributes only; the shared _OPS_CACHE bundle is untouched)
        self.perf.wrap_runner(self.runner)
        # aux-head state deliberately NOT cleared here: it is
        # client-local (like EF residuals) and survives same-shape
        # re-seeds so the local probe keeps converging; _ensure_aux's
        # boundary-shape signature resets it when a re-plan moved the
        # cut (the old head would be probing another tensor)
        if self.codecs.get("rpc") is not None \
                and self._delta_advert is not None:
            # base = the shard EXACTLY as received (the server's shadow
            # holds the same bytes — START params travel fp32 pickled)
            self._delta_base = (
                self._delta_advert,
                jax.tree_util.tree_map(np.asarray, msg.params))
        params = jax.tree_util.tree_map(jnp.asarray, msg.params)
        self.stats = jax.tree_util.tree_map(
            jnp.asarray, msg.batch_stats or {})
        is_final = (msg.end_layer == -1
                    or msg.end_layer >= len(self.runner.model.specs))
        self.frozen, self.trainable = self.runner.partition_params(
            params, is_final)
        if self._overlap_samples:
            # pipelined overlap trained the PREVIOUS seed's shard;
            # this START just re-seeded it, so that shard work never
            # reaches the fold — crediting its samples would inflate
            # this client's FedAvg weight with training the server
            # cannot see.  (The aux head keeps its overlap progress:
            # it is client-local and survives the re-seed.)  A hold
            # START (no params) keeps local weights AND the credit.
            self.log.info(f"overlap: {self._overlap_samples} old-seed "
                          "samples uncounted (shard re-seeded)")
            self._overlap_samples = 0
        if getattr(self.runner, "lora_noop", False):
            self.log.warning(
                "lora_rank set but no target kernels in this shard; "
                "training full shard parameters instead")
        self.opt_state = self.runner.optimizer.init(self.trainable)
        if (sc is not None and sc["mode"] == "reseed"
                and self.stage == 1 and msg.label_counts is not None
                and not (msg.extra or {}).get("refresh")
                and [int(c) for c in msg.label_counts]
                == getattr(self, "_loader_counts", None)
                and sc["batch_size"]
                == self.runner.learning.batch_size):
            # re-seed predicted and got: the overlap's loader clone IS
            # what _build_loader would now rebuild (same subset seed,
            # same counts, same batch geometry) — adopt it, and let
            # the round consume the already-transferred first batches.
            # The speculative stale-seed forwards lose their bet (this
            # START replaced the params): drop the outputs and their
            # rng draws — the runner is fresh-built, so the round's
            # recompute draws from the new stream exactly like a
            # non-overlapped run.
            for ent in sc["items"]:
                ent["rng"] = ent["out"] = None
            self.loader = sc["loader"]
            self._spliced = sc
            self.faults.inc("overlap_splices")
            self.log.info(
                f"sync overlap: {len(sc['items'])} prefetched "
                "batch(es) spliced into this round (loader adopted)")
        else:
            if sc is not None:
                self._discard_sync_cache(sc, runner_kept=False,
                                         loader_kept=False)
            self._build_loader(msg)

    def _build_loader(self, msg: Start):
        """(Re)build the stage-1 data loader from a START's label
        counts: per-client subset seed (clients with identical label
        counts must not train on identical samples), re-salted per
        round under ``distribution.refresh`` — the reference rebuilds
        its loader every START when refresh is on
        (``src/RpcClient.py:108``)."""
        if self.stage == 1 and msg.label_counts is not None:
            from split_learning_tpu.runtime.validation import (
                dataset_kwargs_for_model,
            )
            self.loader = make_data_loader(
                dataset_for_model(self.cfg.model_key),
                self.runner.learning.batch_size,
                distribution=np.asarray(msg.label_counts), train=True,
                seed=subset_seed(self.cfg.seed, self.client_id,
                                 msg.round_idx,
                                 (msg.extra or {}).get("refresh", False)),
                synthetic_size=self.cfg.synthetic_size,
                dataset_kwargs=dataset_kwargs_for_model(
                    self.cfg.model_key, self.cfg.model_kwargs))
            # remembered so a weight-less (hold) START whose plan moved
            # this client's data distribution still rebuilds the loader
            self._loader_counts = [int(c) for c in msg.label_counts]

    def _on_syn(self, msg: Syn):
        self.log.info(f"[<<<] SYN round={msg.round_idx}")
        self.round_ok = True
        self._ok_dev = jnp.asarray(True)
        self.round_idx = msg.round_idx
        # pipelined async rounds: overlap-tick samples survive only a
        # HOLD start (local shard kept — the work is in what the next
        # Update uploads); a re-seeding START zeroed them in
        # _apply_start because the fold never sees that training
        self.num_samples = self._overlap_samples
        self._overlap_samples = 0
        self.gauges.set("round", msg.round_idx)
        # perf plane round window: SYN -> UPDATE published.  The
        # attribution record's components (compute|compile|dispatch|
        # host|wait) sum to this window's wall by construction.
        self.perf.start_round(msg.round_idx)
        # responsive-set overrides (server recomputes after the READY
        # barrier): a dropped previous-stage client must not leave this
        # client waiting on fence copies that will never arrive
        if getattr(msg, "sda_fence_quorum", None) is not None:
            self.sda_fence_quorum = int(msg.sda_fence_quorum)
        if getattr(msg, "sda_feeders", None) is not None:
            self.sda_feeders = list(msg.sda_feeders)
        whole = (self.runner.start_layer == 0
                 and self.runner.model.resolved_end
                 == len(self.runner.model.specs))
        # the round's root span on this participant: hot-loop and
        # publish spans parent under it, so the merged trace's span
        # tree stays connected per round
        with self.tracer.span("client_round", round=msg.round_idx,
                              stage=self.stage):
            if self.stage == 1 and whole:
                pause = self._train_whole()
            elif self._async_mode and self.stage == 1:
                pause = self._train_first_async()
            elif self._async_mode and self.stage == self.n_stages:
                pause = self._train_last_async()
            elif self._async_mode:
                pause = self._train_middle_async()
            elif self.stage == 1:
                pause = self._train_first()
            elif self.stage == self.n_stages:
                pause = self._train_last()
            else:
                pause = self._train_middle()
            if isinstance(pause, _AbortPause):
                # close the perf window (no record emitted mid-abort
                # confusion is avoided by still journaling what ran)
                rec = self.perf.end_round(samples=self.num_samples)
                if rec:
                    self.log.metric(kind="perf", client=self.client_id,
                                    stage=self.stage,
                                    round_idx=msg.round_idx,
                                    aborted=True, **rec)
                self.tracer.flush()
                return   # round abandoned: the server stopped counting us
            if pause is not None and not pause.send_weights:
                # FLEX non-aggregation round (other/FLEX/src/RpcClient
                # .py:110-121): UPDATE still reports samples/result, but
                # carries NO weights — the shard persists locally for
                # the next round
                self._send_update(with_weights=False)
            else:
                self._send_update()
            # close the perf window INSIDE the client_round span so the
            # record's wall matches what the trace shows for this round
            rec = self.perf.end_round(samples=self.num_samples)
            if rec:
                self.log.metric(kind="perf", client=self.client_id,
                                stage=self.stage,
                                round_idx=msg.round_idx, **rec)
        # pipelined rounds: keep ticking locally while the server
        # aggregates/validates and the next START streams in — BEFORE
        # the span flush below, so the overlap window opens while the
        # server's update wall is still running (the flush's file I/O
        # would otherwise eat the head start)
        self._overlap_ticks()
        # a finished round's spans must be durable even if the process
        # dies while idle between rounds
        self.tracer.flush()

    def _send_update(self, with_weights: bool = True):
        # the round's ONE host sync of the NaN sentinel the hot loops
        # accumulated on device (per-batch bool() was a per-tick sync)
        if self._ok_dev is not None and not bool(self._ok_dev):
            self.round_ok = False
        params_h = stats_h = None
        delta_base = None
        if with_weights:
            merged = self.runner.merge_params(self.frozen, self.trainable)
            params_h = jax.tree_util.tree_map(np.asarray, merged)
            stats_h = jax.tree_util.tree_map(np.asarray, self.stats)
            # rpc codec: ship ``trained - base`` against the START's
            # version tag when the chain is intact, full fp32 otherwise
            params_h, delta_base = self._encode_update_wire(params_h)
        # telemetry piggyback: every sync round's UPDATE delivers one
        # fleet sample (counters/gauges/rate) for free, so the server
        # gets end-of-round telemetry even with heartbeats disabled
        tel = self.telemetry.snapshot().as_dict()
        # TENSOR-framed and chunked: a shard UPDATE is the biggest frame
        # a client ever publishes.  Under the aggregator tree the
        # upload lands on this client's L1 group queue; the model
        # allows Update on both rpc and aggregate families.
        dest = RPC_QUEUE
        if getattr(self, "_agg_group", None) is not None:
            dest = aggregate_queue(self.cluster, self._agg_group)
        self._publish_parts(dest, lambda ctx, p=params_h, s=stats_h,
                            n=self.num_samples, ok=self.round_ok,
                            fence=self.fence, cl=self.cluster,
                            db=delta_base, tel=tel:
                            encode_parts(Update(
                                client_id=self.client_id,
                                stage=self.stage, cluster=cl, params=p,
                                batch_stats=s, num_samples=n, ok=ok,
                                round_idx=fence, delta_base=db,
                                # async staleness tag: the generation
                                # these params were seeded from — the
                                # server's admission window reads it
                                version=fence,
                                telemetry=tel),
                                self._chunk_bytes,
                                ctx=ctx), kind="Update")
        # wall-clock anchor for the round-boundary overlap window: the
        # bench intersects [publish, next ctrl] with the server's
        # update/fan-out window on the same host clock
        self._update_pub_t = time.time()
        # error-feedback residuals are part of the client's durable
        # state: checkpoint them with the round (atomic sidecar)
        if self.cfg.checkpoint.save and self.codecs:
            self._save_ef_state()
        self.log.info(f"[>>>] UPDATE samples={self.num_samples} "
                      f"ok={self.round_ok}"
                      + ("" if with_weights else " (no weights)"))
        # failure/recovery counters live per PROCESS: in a multi-process
        # deployment the server can only report its own, so each client
        # surfaces its cumulative stack counters into (its) metrics.jsonl
        # at round end — same diff-successive-records contract
        snap = {k: v for k, v in self.faults.snapshot().items() if v}
        if snap and snap != getattr(self, "_fault_base", None):
            self._fault_base = snap
            self.log.info("round faults (cumulative): " + " ".join(
                f"{k}={v}" for k, v in sorted(snap.items())))
            self.log.metric(kind="faults", client=self.client_id,
                            round_idx=self.round_idx, **snap)
        # wire counters (bytes in/out, encode/decode seconds, sender
        # high-water mark) follow the same contract
        wsnap = {k: v for k, v in self.wire.snapshot().items() if v}
        if wsnap and wsnap != getattr(self, "_wire_base", None):
            self._wire_base = wsnap
            self.log.metric(kind="wire_client", client=self.client_id,
                            round_idx=self.round_idx, **wsnap)
        # fixed-bucket latency percentiles (frame RTT, queue wait, step
        # time, encode/decode) ride metrics.jsonl next to the counters;
        # cumulative like everything above — diff successive records
        hsnap = self.hists.snapshot()
        if hsnap and hsnap != getattr(self, "_hist_base", None):
            self._hist_base = hsnap
            self.log.metric(kind="latency", client=self.client_id,
                            round_idx=self.round_idx, **hsnap)

    def _redeliver_stop(self, msg: Stop) -> Pause:
        """A STOP arriving mid-training: requeue it for the run() loop and
        unwind the hot loop without uploading (the server is shutting
        down; an UPDATE would go nowhere)."""
        self.bus.publish(reply_queue(self.client_id), encode(msg))
        return _AbortPause(send_weights=False)

    def _redeliver_start(self, msg: Start) -> Pause:
        """A START arriving while still in a previous round's loop: the
        server timed this client out of that round and has moved on (its
        barriers no longer count us, so no PAUSE is coming).  Requeue the
        START for the run() loop and unwind without uploading — the
        client then rejoins from the fresh START instead of being lost
        until STOP.

        Async mode instead UPLOADS the round's work before rejoining:
        the Update carries the old seed's version tag, and the server's
        bounded-staleness admission window folds it with a
        staleness-scaled weight — the straggler contributes late
        instead of throwing its round away.  The requeued START is the
        double-buffered next seed, swapped at this tick boundary."""
        self.bus.publish(reply_queue(self.client_id), encode(msg))
        if self._async_mode:
            self.log.info("START mid-round (async): uploading late "
                          "update, swapping seed at tick boundary")
            return Pause(send_weights=True)
        self.log.warning("START while mid-round: rejoining next round")
        return _AbortPause(send_weights=False)

    def _wait_pause(self) -> Pause:
        q = reply_queue(self.client_id)
        while True:
            raw = self.bus.get(q)
            if raw is None:
                continue
            msg = self._decode(raw, q)
            if msg is None:
                continue
            if isinstance(msg, Pause):
                self.log.info("[<<<] PAUSE")
                return msg
            if isinstance(msg, Stop):
                return self._redeliver_stop(msg)
            if isinstance(msg, Start):
                return self._redeliver_start(msg)
            self.log.warning(f"ignoring {type(msg).__name__} while "
                             f"awaiting PAUSE")

    def _check_pause(self) -> Pause | None:
        """Non-blocking-ish control poll from inside a hot loop."""
        raw = self.bus.get(reply_queue(self.client_id), timeout=0.001)
        if raw is None:
            return None
        msg = self._decode(raw, reply_queue(self.client_id))
        if msg is None:
            return None
        if isinstance(msg, Pause):
            return msg
        if isinstance(msg, Stop):
            return self._redeliver_stop(msg)
        if isinstance(msg, Start):
            return self._redeliver_start(msg)
        return None

    # -- async decoupled mode (learning.mode: async) -------------------------

    @property
    def _async_mode(self) -> bool:
        r = getattr(self, "runner", None)
        return r is not None and r.learning.mode == "async"

    def _reset_aux(self) -> None:
        self.aux_params = None
        self.aux_opt_state = None
        self._aux_sig = None
        self._aux_key = None

    def _ensure_aux(self, x) -> None:
        """Shape (or re-shape) the aux head for the current boundary.

        The boundary shape is ``eval_shape`` of this shard's forward on
        the live batch — recomputed only when the shard slice or the
        batch shape moved.  A changed signature means a re-plan moved
        the cut: params AND optimizer state reset (the old moments are
        another tensor's momentum); an unchanged one keeps both, so the
        local probe keeps converging across rounds."""
        r = self.runner
        key = (r.start_layer, r.model.resolved_end,
               tuple(np.shape(leaf)
                     for leaf in jax.tree_util.tree_leaves(x)))
        if self.aux_params is not None \
                and key == getattr(self, "_aux_key", None):
            return
        from split_learning_tpu.ops.auxiliary import aux_shapes_signature
        shapes = jax.eval_shape(r.fwd, self.frozen, self.trainable,
                                self.stats, x, jax.random.key(0))
        sig = aux_shapes_signature(shapes)
        if sig != self._aux_sig:
            if self._aux_sig is not None:
                self.log.info("aux head re-shaped (re-plan moved the "
                              "cut): optimizer state reset")
            self.aux_params = r.init_aux_params(shapes)
            self.aux_opt_state = r.optimizer.init(self.aux_params)
            self._aux_sig = sig
        self._aux_key = key

    def _aux_tick(self, xd, yd, n: int, publish_to: str | None = None):
        """One decoupled training tick: forward + aux loss + immediate
        shard AND head step.  When ``publish_to`` is set the boundary
        output streams downstream as a normal Activation payload (the
        caller wraps it); returns the wire-staged output or None."""
        r = self.runner
        self._ensure_aux(xd)
        rng = r.next_rng()
        sp = self.tracer.start("aux_step", always=False,
                               round=self.round_idx)
        t_sp = time.perf_counter()
        loss, out, gt, ga, self.stats = r.aux_step(
            self.frozen, self.trainable, self.aux_params, self.stats,
            xd, yd, rng)
        self._ok_dev = jnp.logical_and(self._ok_dev,
                                       jnp.isfinite(loss))
        self.trainable, self.opt_state = r.apply_update(
            self.trainable, self.opt_state, gt)
        self.aux_params, self.aux_opt_state = r.apply_update(
            self.aux_params, self.aux_opt_state, ga)
        wire_out = None
        if publish_to is not None:
            wire_out = self._wire_out(out, "intermediate", publish_to)
        sp.end()
        self.hists.observe("step", time.perf_counter() - t_sp)
        self.perf.note_step(t_sp, (loss, self.trainable), n=n)
        self.num_samples += n
        return wire_out

    def _overlap_ticks(self) -> None:
        """Pipelined rounds: after the round's UPDATE leaves, a stage-1
        async client keeps ticking on its CURRENT version (local aux
        steps, nothing published) while the server aggregates/validates
        and the next START streams in — server wall overlaps client
        compute instead of alternating with it.  Bounded to one pass
        over the loader; any control frame ends the overlap and is
        handed back to run() in arrival order.  The extra samples are
        banked for the NEXT round's Update but survive only a hold
        START (shard kept): a re-seed discards the credit along with
        the shard work (_apply_start), while the client-local aux head
        keeps its progress either way."""
        if not self._async_mode:
            # sync twin (learning.sync-overlap): speculative prefetch +
            # stale-seed forward ticks instead of aux training
            self._sync_overlap_ticks()
            return
        if (self.stage != 1
                or self.loader is None or self.aux_params is None):
            return
        from split_learning_tpu.runtime.bus import QueueClosed
        q = reply_queue(self.client_id)
        ticked = 0
        for x, labels in iter(self.loader):
            try:
                raw = self.bus.get(q, timeout=0.0005)
            except (QueueClosed, ConnectionError, OSError):
                # transport gone between rounds: stop ticking and let
                # run()'s own get take the graceful-shutdown path
                # (tracer flush + close), same as a sync client
                return
            if raw is not None:
                self._pending_ctrl.append(raw)
                break
            with self.perf.host():
                xd = jnp.asarray(x)
                yd = jnp.asarray(labels.astype(np.int32))
            self._aux_tick(xd, yd, len(labels))
            # _aux_tick counts into num_samples (already reported in
            # the sent UPDATE) — move the credit to the next round
            self.num_samples -= len(labels)
            self._overlap_samples += len(labels)
            ticked += 1
        if ticked:
            self.log.info(f"async overlap: {ticked} local ticks "
                          f"({self._overlap_samples} samples banked "
                          "for the next round)")

    # -- sync-mode round-boundary overlap (learning.sync-overlap) ------------

    def _overlap_loader_clone(self):
        """The loader a re-seeding next START would build — rebuilt
        HERE, ahead of the START, so the subset draw, epoch shuffle and
        host->device transfers of the next round's first batches all
        run inside the server's update wall.  None when the next
        round's loader is unknowable (refresh re-salts the subset per
        round) or this client has no stage-1 loader."""
        if (self.stage != 1
                or getattr(self, "_loader_counts", None) is None
                or self.cfg.distribution.refresh):
            return None
        from split_learning_tpu.runtime.validation import (
            dataset_kwargs_for_model,
        )
        return make_data_loader(
            dataset_for_model(self.cfg.model_key),
            self.runner.learning.batch_size,
            distribution=np.asarray(self._loader_counts), train=True,
            seed=subset_seed(self.cfg.seed, self.client_id, 0, False),
            synthetic_size=self.cfg.synthetic_size,
            dataset_kwargs=dataset_kwargs_for_model(
                self.cfg.model_key, self.cfg.model_kwargs))

    def _sync_overlap_ticks(self) -> None:
        """Sync-mode pipelined rounds: after the round's UPDATE leaves,
        a stage-1 client keeps working while the server runs its
        round-boundary update (fold finish, FedAvgM, re-shard, START
        fan-out) — the serial bubble that otherwise idles every
        accelerator.

        The client runs the next round's first microbatches — data
        draw, host->device transfer, AND the forward pass — on the
        stale seed, in-flight-window's worth (``control-count``), then
        keeps prefetching further batches.  Two speculative modes,
        predicted from the LAST START's shape:

        * **hold predicted** (FLEX/periodic wire economy): the local
          shard IS the next round's seed and the kept loader IS its
          batch stream — the cached ``(x, rng, out)`` forwards splice
          into the round bit-exactly;
        * **re-seed predicted** (the FedAvg common case): batches come
          from a freshly rebuilt loader clone (the exact sequence a
          re-seeding START's ``_build_loader`` would draw).  The
          forwards are a losing-but-cheap bet (a re-seed replaces the
          params, so their outputs are dropped at the splice and only
          the transferred batches survive), but they are exactly "the
          next round's first microbatches on the stale seed" — the
          compute that fills the server's update wall either way.

        The next ``_on_start`` splices a cache that matches the round
        it actually got and discards anything else — with the rng
        counter and the kept loader's shuffle state restored on
        discard, so an overlapped round stays **bit-identical** to a
        non-overlapped one (tests/test_async.py).  Any control frame
        ends the overlap and is handed back to run() in arrival
        order."""
        r = getattr(self, "runner", None)
        if (r is None or self.stage != 1 or self.loader is None
                or not getattr(r.learning, "sync_overlap", False)):
            return
        whole = (r.start_layer == 0
                 and r.model.resolved_end == len(r.model.specs))
        if whole:
            return   # _train_whole has no splice consumer
        from split_learning_tpu.runtime.bus import QueueClosed
        hold = bool(self._last_start_held)
        cap_fwd = max(1, r.learning.control_count)
        cap = cap_fwd * 4
        counter0 = r._counter
        # the activity window opens HERE: the loader clone build (the
        # next round's subset draw + epoch shuffle) is overlap work too
        t0 = time.time()
        loader_rng0 = None
        if hold:
            src_loader = self.loader
            loader_rng0 = self.loader._rng.bit_generator.state
        else:
            src_loader = self._overlap_loader_clone()
            if src_loader is None:
                return
            # pristine clone state: if an adopted-then-never-trained
            # splice is dropped by a second START, the clone's shuffle
            # stream rewinds to what a fresh _build_loader would hold
            loader_rng0 = src_loader._rng.bit_generator.state
        it = iter(src_loader)
        q = reply_queue(self.client_id)
        items: list[dict] = []
        while len(items) < cap:
            try:
                raw = self.bus.get(q, timeout=0.0005)
            except (QueueClosed, ConnectionError, OSError):
                return   # transport gone between rounds: run() exits
            if raw is not None:
                self._pending_ctrl.append(raw)
                break
            with self.perf.host():
                item = next(it, None)
                if item is not None:
                    x, labels = item
                    xd = jnp.asarray(x)
                    yd = np.asarray(labels, np.int32)
            if item is None:
                break   # epoch exhausted: nothing left to speculate on
            rng = out = None
            if len(items) < cap_fwd:
                # the next round's first microbatch forwards, on the
                # stale seed (both modes — a re-seed drops the outputs
                # at the splice, a hold consumes them bit-exactly)
                rng = r.next_rng()
                sp = self.tracer.start("overlap_fwd", always=False,
                                       round=self.round_idx)
                out = r.fwd(self.frozen, self.trainable, self.stats,
                            xd, rng)
                sp.end()
            items.append({"x": xd, "labels": yd, "rng": rng,
                          "out": out})
        t1 = time.time()
        if not items:
            return
        self._sync_cache = {
            "mode": "hold" if hold else "reseed",
            "loader": None if hold else src_loader,
            "iter": it, "items": items, "counter0": counter0,
            "loader_rng0": loader_rng0,
            "batch_size": r.learning.batch_size,
        }
        # kind=overlap: the activity window the bench intersects with
        # the server's kind=agg/kind=update wall on the shared clock
        self.log.metric(kind="overlap", client=self.client_id,
                        round_idx=self.round_idx,
                        mode=self._sync_cache["mode"],
                        ticks=len(items),
                        t_pub=round(self._update_pub_t, 6),
                        act_t0=round(t0, 6), act_t1=round(t1, 6))
        self.log.info(
            f"sync overlap: {len(items)} speculative "
            f"{'forward' if hold else 'prefetch'} tick(s) while the "
            "server updates")

    def _discard_sync_cache(self, sc: dict, runner_kept: bool,
                            loader_kept: bool) -> None:
        """Unwind a speculation the actual START invalidated: restore
        the rng counter (the kept runner's stream must match a
        non-overlapped round) and the kept loader's shuffle state (the
        overlap consumed an epoch permutation the round now re-draws)."""
        self.faults.inc("overlap_discards")
        if runner_kept and sc["items"] and sc["items"][0]["rng"] \
                is not None:
            self.runner._counter = sc["counter0"]
        if loader_kept and sc["mode"] == "hold" \
                and sc["loader_rng0"] is not None:
            self.loader._rng.bit_generator.state = sc["loader_rng0"]

    def _train_first_async(self) -> Pause:
        """Stage-1 decoupled loop: dispatch + local aux step per batch,
        activations stream downstream, NO gradient wait — the
        gradient queue (and its EF codec) stays dormant."""
        out_qs = self._out_queues()
        n_fwd = 0
        for ep in range(self.epochs):
            self.gauges.set("epoch", ep)
            data_iter = iter(self.loader)
            while True:
                pause = self._check_pause()
                if pause is not None:
                    return pause
                with self.perf.host():
                    item = next(data_iter, None)
                    if item is not None:
                        x, labels = item
                        xd = jnp.asarray(x)
                        yd = jnp.asarray(labels.astype(np.int32))
                if item is None:
                    break
                out_q = out_qs[n_fwd % len(out_qs)]
                out = self._aux_tick(xd, yd, len(labels),
                                     publish_to=out_q)
                _start_host_copy(out)
                labels_np = np.asarray(labels, np.int32)
                data_id = uuid.uuid4().hex
                self._publish_parts(
                    out_q,
                    lambda ctx, out=out, labels_np=labels_np, d=data_id,
                    fence=self.fence, cl=self.cluster:
                        encode_parts(Activation(
                            data_id=d,
                            data=self._wire_host(out, "intermediate"),
                            labels=labels_np, trace=[self.client_id],
                            cluster=cl, round_idx=fence),
                            self._chunk_bytes, ctx=ctx),
                    kind="Activation")
                n_fwd += 1
            # epoch fence, unconditionally in async (not just strict
            # SDA): downstream PAUSE drains exit the moment every
            # feeder's final fence arrives instead of idling out
            # ASYNC_DRAIN_IDLE_S — per-queue FIFO orders it after
            # every activation it covers
            for q in out_qs:
                self.bus.publish(q, encode(EpochEnd(
                    client_id=self.client_id, round_idx=self.fence,
                    epoch=ep)))
        self.bus.publish(RPC_QUEUE, encode(Notify(
            client_id=self.client_id, cluster=self.cluster,
            round_idx=self.fence)))
        self.log.info(f"[>>>] NOTIFY fwd={n_fwd} (async)")
        return self._wait_pause()

    def _drained(self, fenced: set, last_rx: float) -> bool:
        """PAUSE-drain exit test for async consumers: every origin
        feeder's final epoch fence arrived (per-queue FIFO: nothing
        the fences cover is still upstream), or the in-queue idled
        past the grace (a delayed stream's tail beyond it is dropped —
        the bounded-staleness liveness contract)."""
        feeders = set(self.sda_feeders or ())
        if feeders and feeders <= fenced:
            return True
        return time.monotonic() - last_rx > ASYNC_DRAIN_IDLE_S

    def _train_middle_async(self) -> Pause:
        """Middle-stage decoupled loop: consume upstream activations,
        local aux step, forward downstream.  EpochEnd markers relay
        downstream AND fence this stage's PAUSE drain — a Pause does
        not abandon the in-flight stream (the feeders NOTIFY the
        moment they exhaust their data, well before a slow wire has
        delivered everything they sent)."""
        in_q = intermediate_queue(self.stage - 1, self.cluster,
                                  self.pair)
        out_qs = self._out_queues()
        n_fwd = 0
        fenced: set = set()
        paused: Pause | None = None
        last_rx = time.monotonic()
        while True:
            if paused is None:
                pause = self._check_pause()
                if isinstance(pause, _AbortPause):
                    return pause      # round abandoned: nothing to drain
                if pause is not None:
                    paused = pause
                    last_rx = time.monotonic()
            elif self._drained(fenced, last_rx):
                self.log.info("[<<<] PAUSE (stream drained)")
                return paused
            raw = self.bus.get(in_q, timeout=0.001)
            if raw is None:
                continue
            act = self._decode(raw, in_q)
            if act is None or act.round_idx != self.fence:
                continue
            last_rx = time.monotonic()
            if isinstance(act, EpochEnd):
                if act.epoch >= self.epochs - 1:
                    fenced.add(act.client_id)
                for q in out_qs:
                    self.bus.publish(q, raw)  # slcheck: wire=EpochEnd
                continue
            xd = _from_wire_tree(act.data)
            yd = jnp.asarray(act.labels, jnp.int32)
            out_q = out_qs[n_fwd % len(out_qs)]
            out = self._aux_tick(xd, yd, len(act.labels),
                                 publish_to=out_q)
            _start_host_copy(out)
            self._publish_parts(
                out_q,
                lambda ctx, out=out, act=act, fence=self.fence,
                cl=self.cluster:
                    encode_parts(Activation(
                        data_id=act.data_id,
                        data=self._wire_host(out, "intermediate"),
                        labels=act.labels,
                        trace=list(act.trace) + [self.client_id],
                        cluster=cl, round_idx=fence),
                        self._chunk_bytes, ctx=ctx),
                kind="Activation")
            n_fwd += 1

    def _train_last_async(self) -> Pause:
        """Final-stage decoupled loop: true loss + local step per
        received batch, NO input-gradient return (the whole point) —
        reuses the whole-model step, which takes gradients wrt the
        trainables only.  PAUSE starts a bounded drain (``_drained``):
        the feeders NOTIFY the moment their data is dispatched, so the
        head's input stream is still in flight when the round closes —
        it eats until every feeder's final epoch fence lands or the
        queue idles out."""
        r = self.runner
        in_q = intermediate_queue(self.stage - 1, self.cluster,
                                  self.pair)
        fenced: set = set()
        paused: Pause | None = None
        last_rx = time.monotonic()
        while True:
            if paused is None:
                pause = self._check_pause()
                if isinstance(pause, _AbortPause):
                    return pause      # round abandoned: nothing to drain
                if pause is not None:
                    paused = pause
                    last_rx = time.monotonic()
            elif self._drained(fenced, last_rx):
                self.log.info("[<<<] PAUSE (stream drained)")
                return paused
            raw = self.bus.get(in_q, timeout=0.001)
            if raw is None:
                continue
            act = self._decode(raw, in_q)
            if act is None or act.round_idx != self.fence:
                continue
            last_rx = time.monotonic()
            if isinstance(act, EpochEnd):
                if act.epoch >= self.epochs - 1:
                    # the feeder's last fence: its stream is fully in
                    fenced.add(act.client_id)
                continue
            x = _from_wire_tree(act.data)
            labels = jnp.asarray(act.labels, jnp.int32)
            sp = self.tracer.start("sda_step", always=False,
                                   round=self.round_idx, window=1)
            t_sp = time.perf_counter()
            loss, gt, self.stats = r.whole_step(
                self.frozen, self.trainable, self.stats, x, labels,
                r.next_rng())
            self._ok_dev = jnp.logical_and(self._ok_dev,
                                           jnp.isfinite(loss))
            self.trainable, self.opt_state = r.apply_update(
                self.trainable, self.opt_state, gt)
            sp.end()
            self.hists.observe("step", time.perf_counter() - t_sp)
            self.perf.note_step(t_sp, (loss, self.trainable),
                                n=len(act.labels))
            self.num_samples += len(act.labels)

    # -- hot loops -----------------------------------------------------------

    def _train_whole(self) -> Pause:
        r = self.runner
        for _ in range(self.epochs):
            data_iter = iter(self.loader)
            while True:
                # loader fetch + host->device conversion land in the
                # perf plane's host-data attribution component
                with self.perf.host():
                    item = next(data_iter, None)
                    if item is not None:
                        x, labels = item
                        xd = jnp.asarray(x)
                        yd = jnp.asarray(labels.astype(np.int32))
                if item is None:
                    break
                t_sp = time.perf_counter()
                loss, grads, self.stats = r.whole_step(
                    self.frozen, self.trainable, self.stats,
                    xd, yd, r.next_rng())
                # folded on DEVICE; synced once in _send_update — a
                # bool() here would stall the loop every batch
                self._ok_dev = jnp.logical_and(self._ok_dev,
                                               jnp.isfinite(loss))
                self.trainable, self.opt_state = r.apply_update(
                    self.trainable, self.opt_state, grads)
                self.hists.observe("step", time.perf_counter() - t_sp)
                # sampled device fence lives INSIDE the perf plane
                # (runtime/perf.py SampledStepTimer), behind the sampler gate
                self.perf.note_step(t_sp, (loss, self.trainable),
                                    n=len(labels))
                self.num_samples += len(labels)
        self.bus.publish(RPC_QUEUE, encode(Notify(
            client_id=self.client_id, cluster=self.cluster,
            round_idx=self.fence)))
        return self._wait_pause()

    def _epoch_items(self, ep: int):
        """One epoch's ``(x, labels, cached)`` stream for the stage-1
        hot loop.  Epoch 0 consumes the sync-overlap splice first —
        ``cached`` carries the speculative ``{rng, out}`` when the
        forward was precomputed on the held seed (or just the
        device-resident batch on a re-seed round) — then continues the
        overlap's own iterator, which IS the round's epoch-0 sequence
        (same shuffle draw).  No splice: the plain loader epoch."""
        sp = self._spliced if ep == 0 else None
        if sp is not None:
            self._spliced = None
            for ent in sp["items"]:
                yield ent["x"], ent["labels"], ent
            for x, labels in sp["iter"]:
                yield x, labels, None
        else:
            for x, labels in iter(self.loader):
                yield x, labels, None

    def _train_first(self) -> Pause:
        """Bounded-in-flight 1F1B streaming (``src/train/VGG16.py:61-136``)."""
        r = self.runner
        inflight: dict[str, _Inflight] = {}
        grad_q = gradient_queue(self.stage, self.client_id)
        out_qs = self._out_queues()
        cap = max(1, r.learning.control_count)
        n_fwd = n_bwd = 0

        def fence_epoch(ep: int):
            # strict-SDA epoch fence: the head's hard window drains
            # leftovers only on this marker.  Published right AFTER the
            # final activation (per-queue FIFO orders it last) and
            # BEFORE this client's gradient wait — the leftover
            # batches' gradients are exactly what that wait needs, so
            # fencing any later would deadlock the barrier.
            if self.sda_strict and self.sda_size > 1:
                for q in out_qs:
                    self.bus.publish(q, encode(EpochEnd(
                        client_id=self.client_id,
                        round_idx=self.fence, epoch=ep)))

        for ep in range(self.epochs):
            self.gauges.set("epoch", ep)
            data_iter = self._epoch_items(ep)
            # prefetch one batch: exhaustion must be known at the LAST
            # dispatch, not when the in-flight cap next frees — with a
            # strict head holding this feeder's batches, the cap never
            # frees until the fence goes out
            with self.perf.host():
                next_item = next(data_iter, None)
            exhausted = next_item is None
            if exhausted:
                fence_epoch(ep)   # empty loader: fence immediately
            while not (exhausted and n_fwd == n_bwd):
                raw = self.bus.get(grad_q, timeout=0.0005)
                if raw is not None:
                    g = self._decode(raw, grad_q)
                    if g is None or g.round_idx != self.fence:
                        continue   # corrupt, or from a dropped round
                    ent = inflight.pop(g.data_id, None)
                    if ent is None:   # no longer tracked (cut round)
                        continue
                    sp = self.tracer.start("bwd", always=False,
                                           round=self.round_idx)
                    t_sp = time.perf_counter()
                    gt, _, self.stats = r.bwd(
                        self.frozen, self.trainable, self.stats, ent.x,
                        _from_wire_tree(g.data), ent.rng)
                    self.trainable, self.opt_state = r.apply_update(
                        self.trainable, self.opt_state, gt)
                    sp.end()
                    self.hists.observe("step",
                                       time.perf_counter() - t_sp)
                    self.perf.note_step(t_sp, (self.trainable,),
                                        n=ent.n)
                    n_bwd += 1
                    # counted here, not at dispatch: a mid-loop PAUSE
                    # abandons in-flight forwards, and the FedAvg weight
                    # must only cover samples whose update was applied
                    self.num_samples += ent.n
                    self.gauges.set("inflight", len(inflight))
                    continue
                if exhausted or len(inflight) >= cap:
                    # truly idle (no gradient, nothing to dispatch): check
                    # for early PAUSE/STOP (downstream died or the server
                    # dropped the round) rather than waiting forever for
                    # gradients that will never come — the reference hangs
                    # here (SURVEY.md §5.3).  Kept off the dispatch path so
                    # steady-state forwards pay no extra RPC.
                    pause = self._check_pause()
                    if pause is not None:
                        self.log.warning(
                            f"PAUSE mid-loop with {len(inflight)} in flight")
                        return pause
                    continue
                x, labels, cached = next_item
                with self.perf.host():
                    next_item = next(data_iter, None)
                    x = jnp.asarray(x)
                out_q = out_qs[n_fwd % len(out_qs)]
                sp = self.tracer.start("fwd", always=False,
                                       round=self.round_idx,
                                       spliced=bool(
                                           cached
                                           and cached["out"]
                                           is not None))
                if cached is not None and cached["out"] is not None:
                    # sync-overlap splice: this microbatch's forward
                    # already ran on the held seed during the server's
                    # update wall — consume it (the rng it drew is the
                    # stream's next draw, so the sequence matches a
                    # non-overlapped round bit-for-bit)
                    rng = cached["rng"]
                    out = self._wire_out(cached["out"], "intermediate",
                                         out_q)
                else:
                    rng = r.next_rng()
                    out = self._wire_out(
                        r.fwd(self.frozen, self.trainable, self.stats,
                              x, rng), "intermediate", out_q)
                sp.end()
                data_id = uuid.uuid4().hex
                inflight[data_id] = _Inflight(x=x, rng=rng,
                                              trace=[self.client_id],
                                              n=len(labels))
                self.gauges.set("inflight", len(inflight))
                # double buffer: start the non-blocking device→host
                # copy now and hand the encode+send to the async
                # sender; this thread moves straight on to batch k+1's
                # dispatch (or the next gradient) while batch k drains
                _start_host_copy(out)
                labels_np = np.asarray(labels, np.int32)
                # bind fence/cluster NOW: the thunk may run after an
                # abandoned round's _on_start moved them
                self._publish_parts(
                    out_q,
                    lambda ctx, out=out, labels_np=labels_np, d=data_id,
                    fence=self.fence, cl=self.cluster:
                        encode_parts(Activation(
                            data_id=d,
                            data=self._wire_host(out, "intermediate"),
                            labels=labels_np, trace=[self.client_id],
                            cluster=cl, round_idx=fence),
                            self._chunk_bytes, ctx=ctx),
                    kind="Activation")
                n_fwd += 1
                if next_item is None:
                    exhausted = True
                    fence_epoch(ep)
        self.bus.publish(RPC_QUEUE, encode(Notify(
            client_id=self.client_id, cluster=self.cluster,
            round_idx=self.fence)))
        self.log.info(f"[>>>] NOTIFY fwd={n_fwd} bwd={n_bwd}")
        return self._wait_pause()

    def _out_queues(self) -> list[str]:
        """Forward-dispatch queues: the next stage's per-device queues
        (DCSL round-robin scatter) when ``sda_peers`` is set, else the
        single shared/pair-indexed cluster queue.

        The rotation start is staggered by a stable hash of this
        client's id: with a small in-flight cap, producers all starting
        at peer 0 would convoy onto the same head each turn instead of
        load-balancing across heads."""
        if self.sda_peers:
            qs = [intermediate_queue(self.stage, self.cluster, p)
                  for p in self.sda_peers]
            off = zlib.crc32(self.client_id.encode()) % len(qs)
            return qs[off:] + qs[:off]
        return [intermediate_queue(self.stage, self.cluster, self.pair)]

    def _train_middle(self) -> Pause:
        r = self.runner
        in_q = intermediate_queue(self.stage - 1, self.cluster, self.pair)
        out_qs = self._out_queues()
        n_fwd = 0
        # strict-SDA fences crossing a middle stage: relay each
        # (origin, epoch) marker downstream exactly once, and only at
        # the full previous-stage quorum — every activation the marker
        # fences has then ALREADY been forwarded (this loop forwards on
        # receipt, per-queue FIFO), keeping the feeder→head ordering
        # guarantee hop by hop even when parallel previous-stage
        # devices relay at different speeds.
        fence_copies: dict[tuple[str, int], int] = {}
        quorum = max(1, self.sda_fence_quorum)
        grad_q = gradient_queue(self.stage, self.client_id)
        inflight: dict[str, _Inflight] = {}
        while True:
            pause = self._check_pause()
            if pause is not None:
                self.log.info("[<<<] PAUSE")
                return pause
            raw = self.bus.get(grad_q, timeout=0.0005)
            if raw is not None:
                g = self._decode(raw, grad_q)
                if g is None or g.round_idx != self.fence:
                    continue   # corrupt, or from a dropped round
                ent = inflight.pop(g.data_id, None)
                if ent is None:   # no longer tracked (cut round)
                    continue
                sp = self.tracer.start("bwd", always=False,
                                       round=self.round_idx)
                t_sp = time.perf_counter()
                gt, gx, self.stats = r.bwd(
                    self.frozen, self.trainable, self.stats, ent.x,
                    _from_wire_tree(g.data), ent.rng)
                self.trainable, self.opt_state = r.apply_update(
                    self.trainable, self.opt_state, gt)
                sp.end()
                self.hists.observe("step", time.perf_counter() - t_sp)
                self.perf.note_step(t_sp, (self.trainable,), n=ent.n)
                self.num_samples += ent.n   # see _train_first
                origin = ent.trace[-1]
                grad_out_q = gradient_queue(self.stage - 1, origin)
                gx = self._wire_out(gx, "gradient", grad_out_q)
                _start_host_copy(gx)
                self._publish_parts(
                    grad_out_q,
                    lambda ctx, gx=gx, d=g.data_id, tr=ent.trace[:-1],
                    fence=self.fence:
                        encode_parts(Gradient(
                            data_id=d,
                            data=self._wire_host(gx, "gradient"),
                            trace=tr, round_idx=fence),
                            self._chunk_bytes, ctx=ctx),
                    kind="Gradient")
                continue
            raw = self.bus.get(in_q, timeout=0.0005)
            if raw is None:
                continue
            act = self._decode(raw, in_q)
            if act is None or act.round_idx != self.fence:
                continue   # corrupt, or from a dropped round: discard
            if isinstance(act, EpochEnd):
                key = (act.client_id, act.epoch)
                fence_copies[key] = fence_copies.get(key, 0) + 1
                if fence_copies[key] == quorum:
                    for q in out_qs:   # fence ALL downstream devices
                        self.bus.publish(q, raw)  # slcheck: wire=EpochEnd
                continue
            x = _from_wire_tree(act.data)
            rng = r.next_rng()
            out_q = out_qs[n_fwd % len(out_qs)]
            sp = self.tracer.start("fwd", always=False,
                                   round=self.round_idx)
            out = self._wire_out(
                r.fwd(self.frozen, self.trainable, self.stats, x, rng),
                "intermediate", out_q)
            sp.end()
            inflight[act.data_id] = _Inflight(x=x, rng=rng,
                                              trace=list(act.trace),
                                              n=len(act.labels))
            self.gauges.set("queue_depth", len(inflight))
            _start_host_copy(out)
            self._publish_parts(
                out_q,
                lambda ctx, out=out, act=act, fence=self.fence,
                cl=self.cluster:
                    encode_parts(Activation(
                        data_id=act.data_id,
                        data=self._wire_host(out, "intermediate"),
                        labels=act.labels,
                        trace=list(act.trace) + [self.client_id],
                        cluster=cl, round_idx=fence),
                        self._chunk_bytes, ctx=ctx),
                kind="Activation")
            n_fwd += 1

    def _train_last(self) -> Pause:
        """Loss + backward + routed input-gradient return
        (``src/train/VGG16.py:138-191``); with ``sda_size > 1`` collects a
        window of client batches and runs them as ONE concatenated fwd/bwd
        (DCSL SDA, ``other/DCSL/src/Scheduler.py:152-191``)."""
        r = self.runner
        in_q = intermediate_queue(self.stage - 1, self.cluster, self.pair)
        # DCSL window semantics (other/DCSL/src/Scheduler.py:152-191):
        # one batch from each of ``sda_size`` DISTINCT origins.  pending
        # holds per-origin FIFOs — a second batch from an origin already
        # represented waits for the NEXT window instead of widening this
        # one, mirroring the reference's per-device queues.
        pending: dict[str, list[Activation]] = {}
        idle_flush_s = 0.25
        idle_since: float | None = None
        # aggregation.sda-strict picks the barrier discipline:
        #
        # * ELASTIC (default): the width ADAPTS — it starts at
        #   sda_size, and an idle-triggered partial flush (a feeder ran
        #   dry — uneven non-IID loaders make that the common case, not
        #   just the round tail) lowers it to the surviving feeder
        #   count so each subsequent burst doesn't re-pay the idle
        #   stall; it rises back toward sda_size the moment more
        #   distinct origins are live again.
        # * STRICT (DCSL parity, other/DCSL/src/Scheduler.py:152-191):
        #   a HARD sda_size distinct-origin barrier — a slow-but-alive
        #   feeder is waited for, and leftovers drain only when every
        #   origin still holding batches has fenced its epoch
        #   (EpochEnd marker) or the round PAUSEs.
        strict = self.sda_strict
        target = max(1, self.sda_size)
        n_epochs = max(1, self.epochs)
        # per-origin epoch-fence counts: an origin is out of the game
        # only once it has fenced EVERY epoch of the round — a feeder
        # that fenced epoch k < n still sends epoch k+1 batches, and
        # cross-epoch windows are legitimate (the reference's scheduler
        # pairs whatever distinct devices' batches are queued)
        fences: dict[str, int] = {}
        self._sda_fences = fences   # observability (tests assert the
                                    # strict drain is fence-gated)
        # (origin, epoch) -> copies received.  In >2-stage plans every
        # stage-(n-1) device relays one deduplicated copy of each
        # feeder's fence, so a fence is RECORDED only at the full
        # quorum: the first copy can overtake activations relayed via a
        # slower middle device, but the LAST copy's per-queue FIFO
        # position proves every middle-routed batch it fences is
        # already in.  Counting raw arrivals would both overshoot
        # n_epochs and record fences early.
        fence_copies: dict[tuple[str, int], int] = {}
        quorum = max(1, self.sda_fence_quorum)

        def live() -> list[str]:
            return [o for o, q in pending.items() if q]

        def pop_window(require_full: bool) -> list[Activation] | None:
            # sorted, NOT arrival order: the window's concat order feeds
            # the jitted step, and a deterministic order is what lets a
            # chaos run's aggregated params match the fault-free run
            # bit-for-bit (tests/test_chaos.py) — arrival order is
            # thread-scheduling noise even without faults
            origins = sorted(live())
            if not origins or (require_full and len(origins) < target):
                return None
            return [pending[o].pop(0)
                    for o in origins[:max(1, self.sda_size)]]

        def drain_dead_barrier():
            # strict: leftovers drain exactly when a full window can
            # NEVER form again — the origins that could still
            # contribute (feeders with unfenced epochs left, plus
            # anything already buffered) no longer reach the barrier
            # width.  Waiting longer would deadlock the feeders'
            # gradient waits; draining sooner would break the barrier
            # for a slow-but-alive feeder (the whole point of strict).
            feeders = set(self.sda_feeders or ()) or set(pending)
            while True:
                possible = ({o for o in feeders
                             if fences.get(o, 0) < n_epochs}
                            | set(live()))
                if len(possible) >= target:
                    return
                w = pop_window(require_full=False)
                if not w:
                    return
                self._sda_step(w)

        while True:
            pause = self._check_pause()
            if pause is not None:
                while True:   # drain everything buffered before PAUSE
                    w = pop_window(require_full=False)
                    if not w:
                        break
                    self._sda_step(w)
                self.log.info("[<<<] PAUSE")
                return pause
            raw = self.bus.get(in_q, timeout=0.001)
            if raw is None:
                if strict:
                    continue   # hard barrier: block until traffic,
                               # an epoch fence, or PAUSE
                # the window is a BARRIER in steady state, but a
                # starved barrier must not deadlock stage-1's gradient
                # wait — flush a partial window after a real idle spell
                # and adapt the barrier down to what is actually alive
                now = time.monotonic()
                idle_since = idle_since if idle_since is not None else now
                if now - idle_since >= idle_flush_s:
                    w = pop_window(require_full=False)
                    if w:
                        target = max(1, len(w))
                        self._sda_step(w)
                continue
            act = self._decode(raw, in_q)
            if act is None or act.round_idx != self.fence:
                continue   # corrupt, or from a dropped round: discard
            if isinstance(act, EpochEnd):
                key = (act.client_id, act.epoch)
                fence_copies[key] = fence_copies.get(key, 0) + 1
                if fence_copies[key] == quorum:
                    fences[act.client_id] = fences.get(act.client_id,
                                                       0) + 1
                if strict:
                    # full windows buffered at fence time must pop as
                    # WINDOWS, not wait to be drained as dead-barrier
                    # partials — keeps the code safe even if the
                    # arrival-time pop policy changes (ADVICE r4); loop
                    # until dry so a backlog can't strand windows
                    while True:
                        w = pop_window(require_full=True)
                        if not w:
                            break
                        self._sda_step(w)
                    drain_dead_barrier()
                continue
            # reset the idle clock only for CURRENT-round traffic — a
            # stream of stale activations must not starve the tail flush
            idle_since = None
            # window identity is the ROOT origin (trace[0], the stage-1
            # feeder = the DCSL "device"), not the immediate sender: in
            # a >2-stage plan trace[-1] is a middle device and every
            # batch would share it, so a distinct-origin window could
            # never widen past the middle-stage client count.  Gradient
            # routing below still uses trace[-1] (hop-by-hop return).
            pending.setdefault(act.trace[0], []).append(act)
            self.gauges.set("queue_depth",
                            sum(len(q) for q in pending.values()))
            n_live = len(live())
            if n_live > target:
                target = min(max(1, self.sda_size), n_live)
            w = pop_window(require_full=True)
            if w:
                self._sda_step(w)
            elif strict:
                # a batch buffered behind a dead barrier (every other
                # feeder fully fenced) must not wait for a fence that
                # already happened
                drain_dead_barrier()

    def _sda_step(self, window: list[Activation]):
        r = self.runner
        sizes = [len(a.labels) for a in window]
        sp = self.tracer.start("sda_step", always=False,
                               round=self.round_idx,
                               window=len(window))
        t_sp = time.perf_counter()
        # boundary payloads may be pytrees (mask-carrying models):
        # concatenate per leaf along the batch axis, split grads back
        x = jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs),
            *[_from_wire_tree(a.data) for a in window])
        labels = jnp.concatenate(
            [jnp.asarray(a.labels, jnp.int32) for a in window])
        loss, gt, gx, self.stats = r.last_step(
            self.frozen, self.trainable, self.stats, x, labels,
            r.next_rng())
        # NaN sentinel (src/train/VGG16.py:169), folded on DEVICE and
        # synced once per round in _send_update (slcheck JX001)
        self._ok_dev = jnp.logical_and(self._ok_dev,
                                       jnp.isfinite(loss))
        self.trainable, self.opt_state = r.apply_update(
            self.trainable, self.opt_state, gt)
        sp.end()
        self.hists.observe("step", time.perf_counter() - t_sp)
        self.perf.note_step(t_sp, (loss, self.trainable),
                            n=int(sum(sizes)))
        self.num_samples += int(sum(sizes))
        grad_codec = self.codecs.get("gradient")
        if grad_codec is None:
            # plain wire: one whole-window device cast + host copy,
            # sliced after.  With a codec the dense window never
            # crosses to host — only the per-part prepared leaves do.
            gx = _cast_for_wire(gx, self._dev_cast)
            _start_host_copy(gx)
        off = 0
        for act, n in zip(window, sizes):
            # slice the raw cotangent, THEN wire-encode the part:
            # quantized/sparse wrapper leaves don't slice, per-part
            # quantization scales are tighter than one window-wide
            # scale, and the EF residual must be per ORIGIN stream
            gx_part = jax.tree_util.tree_map(
                lambda a, off=off, n=n: a[off:off + n], gx)
            off += n
            origin = act.trace[-1]
            grad_out_q = gradient_queue(self.stage - 1, origin)
            if grad_codec is not None:
                wire_part = self._wire_out(gx_part, "gradient",
                                           grad_out_q)
                _start_host_copy(wire_part)
            else:
                wire_part = gx_part   # already cast + copying above
            self._publish_parts(
                grad_out_q,
                lambda ctx, wp=wire_part, act=act, fence=self.fence:
                    encode_parts(Gradient(
                        data_id=act.data_id,
                        data=self._wire_host(wp, "gradient"),
                        trace=list(act.trace)[:-1], round_idx=fence),
                        self._chunk_bytes, ctx=ctx),
                kind="Gradient")


def main(argv=None):
    from split_learning_tpu.platform import apply_platform_env
    apply_platform_env()
    ap = argparse.ArgumentParser(
        description="Split-learning protocol client (reference client.py "
                    "parity).")
    ap.add_argument("--config", default="config.yaml")
    ap.add_argument("--layer_id", type=int, required=True,
                    help="1-based stage index")
    ap.add_argument("--client_id", default=None)
    ap.add_argument("--cluster", type=int, default=None)
    ap.add_argument("--profile", default=None,
                    help="path to profiling.json (optional)")
    args = ap.parse_args(argv)
    cfg = from_yaml(args.config)
    from split_learning_tpu.platform import apply_compile_cache
    apply_compile_cache(cfg.compile_cache_dir)
    profile = None
    if args.profile:
        import json
        with open(args.profile) as f:
            profile = json.load(f)
    client_id = args.client_id or f"client_{args.layer_id}_{uuid.uuid4().hex[:6]}"
    blackbox.install(cfg, client_id, role="client")
    client = ProtocolClient(cfg, client_id, args.layer_id,
                            cluster=args.cluster, profile=profile)
    client.run()


if __name__ == "__main__":
    main()
