"""Multi-process protocol server: the reference's ``server.py`` +
``src/Server.py`` FSM over real transports.

The server is a :class:`ProtocolContext` — a
:class:`~split_learning_tpu.runtime.context.TrainContext` whose
``train_cluster`` drives REMOTE clients through the control protocol
instead of running the compiled mesh step locally.  Because it satisfies
the same interface, all six round strategies
(:mod:`split_learning_tpu.runtime.strategies`) work unchanged over a
live deployment — the reference needed a full server fork per algorithm
(SURVEY.md §2.3).

Round choreography parity (``/root/reference/src/Server.py``):
registration barrier (``:111-135``) → planning (``:300-382``) → per-round
START with shard weights (``:214-298``) → READY barrier (replacing the
25 s sleep at ``:289``) → SYN (``:290-296``) → NOTIFY collection → PAUSE
fan-out (``:137-153``) → UPDATE collection (``:155-170``) → strategy
aggregation → validation + checkpoint (``:182-196``, via the shared round
loop in :mod:`split_learning_tpu.runtime.loop`).

Failure-detection improvement over the reference (SURVEY.md §5.3: a
crashed client hangs the round forever): every barrier carries a
deadline; clients that miss it are dropped from the round with a logged
warning instead of wedging the server.
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
import os
import threading
import time
import zlib
from typing import Any, Callable

import numpy as np

from split_learning_tpu.config import Config, from_yaml
from split_learning_tpu.models import shard_params
from split_learning_tpu.parallel.mesh import stage_ranges
from split_learning_tpu.runtime.bus import Broker, Transport
from split_learning_tpu.runtime.context import MeshContext
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.loop import TrainResult, run_training
from split_learning_tpu.runtime.plan import (
    ClusterPlan, Registration, plan_clusters,
)
from split_learning_tpu.runtime import aggregate as agg_plane
from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.protocol import (
    AggAssign, AggFlush, AggHello, BlackboxDump, DigestRoute,
    FleetDigest, FrameAssembler, Heartbeat, Notify, PartialAggregate,
    Pause, Ready, Register, StageAssign, StageHello, Start, Stop, Syn,
    Update, digest_queue, encode, encode_parts, reply_queue, RPC_QUEUE,
)
from split_learning_tpu.runtime.spans import unpack_ctx
from split_learning_tpu.runtime.telemetry import FleetMonitor, GaugeSet


class RoundTimeout(RuntimeError):
    pass


class _StageHostLost(RuntimeError):
    """Raised from inside a round attempt's barriers when an assigned
    stage host (pipeline.remote) died — its spawned process exited or
    the FleetMonitor marked it ``lost``.  Caught by ``train_cluster``'s
    retry wrapper: the dead host's slots are re-assigned to a survivor
    under the SAME client ids and the attempt re-runs behind a bumped
    generation fence (every barrier frame is gen-fenced, so the aborted
    attempt's stragglers drop on arrival and the re-run's fold is
    bit-identical to a fault-free round)."""

    def __init__(self, host_id: str):
        super().__init__(f"stage host {host_id} lost mid-round")
        self.host_id = host_id


class ProtocolContext(MeshContext):
    """Server-side TrainContext that trains via remote protocol clients.

    Validation / init reuse the in-process implementations (the server
    holds the full model for reassembly + test passes, exactly like the
    reference's ``src/val/get_val.py``).
    """

    clients_hold_state = True   # remote shards persist between rounds
    # the in-process device-resident fast path MUST NOT hijack protocol
    # rounds — training happens on remote clients, not the server's mesh
    train_cluster_resident = None

    def __init__(self, cfg: Config, transport: Transport,
                 logger: Logger | None = None,
                 client_timeout: float = 600.0,
                 ready_timeout: float | None = None):
        super().__init__(cfg)
        if self._parallel_axis() is not None:
            # fail fast like require_profiles: protocol clients build
            # plain unsharded ShardRunners — silently dropping the
            # configured TP/SP/EP axis would train in a different regime
            # than the YAML states (and OOM at real model scale)
            name, n = self._parallel_axis()
            raise ValueError(
                f"topology.{name}-parallel={n} is only supported by the "
                "in-process mesh backend (python -m split_learning_tpu"
                ".run); the multi-process protocol deployment does not "
                "shard client models yet")
        self.bus = transport
        from split_learning_tpu.runtime.spans import make_tracer
        from split_learning_tpu.runtime.trace import (
            HistogramSet, default_fault_counters, default_wire_counters,
        )
        self.faults = getattr(transport, "faults", None) \
            or default_fault_counters
        self.wire = getattr(transport, "wire", None) \
            or default_wire_counters
        self.tracer = getattr(transport, "tracer", None) \
            or make_tracer(cfg, "server")
        self.hists = getattr(transport, "hists", None) or HistogramSet()
        self._fault_base: dict = {}   # snapshot at the last round log
        self._assembler = FrameAssembler()   # chunked UPDATE reassembly
        self.log = logger or Logger.for_run(cfg, "server",
                                            console=False)
        # live fleet telemetry (runtime/telemetry.py): per-client
        # health state machine + time series fed by HEARTBEAT frames
        # (and the snapshot piggybacked on every Update).  The round
        # barriers consult it so a `lost` client is dropped after
        # observability.liveness-timeout instead of stalling to the
        # full client_timeout.  None when heartbeats are disabled.
        self.gauges = GaugeSet()
        obs = getattr(cfg, "observability", None)
        self.fleet = None
        if obs is not None and obs.heartbeat_interval > 0:
            self.fleet = FleetMonitor(
                interval=obs.heartbeat_interval,
                liveness_timeout=obs.liveness_timeout,
                log=self.log, gauges=self.gauges, faults=self.faults,
                watchlist_size=obs.watchlist_size)
        # hierarchical heartbeat roll-up (observability.digest-interval
        # > 0): clients are routed to an adopted aggregator node's
        # digest queue via START extra.digest and the node's
        # FleetDigest frames replace their individual heartbeats at
        # this pump.  _digest_route maps client -> node; a dead node's
        # clients are re-pointed to direct heartbeats (DigestRoute
        # frames) and its queue drained here, counted, so the fallback
        # can never mint a phantom `lost`.
        self._digest_interval = (obs.digest_interval
                                 if obs is not None else 0.0)
        self._digest_route: dict[str, str] = {}
        self._digest_dead: set = set()
        self._dead_drains: dict[str, FrameAssembler] = {}
        self._digest_check_t = 0.0
        self.client_timeout = client_timeout
        # registration/READY happen before any jit work on the client, so
        # they can run on a much shorter deadline than the training
        # barriers (NOTIFY/UPDATE), which cover compile + a full round
        self.ready_timeout = (client_timeout if ready_timeout is None
                              else ready_timeout)
        self._registrations: dict[str, Registration] = {}
        self._ready: set = set()
        self._notified: set = set()
        self._updates: list[Update] = []
        # delta-encoded Updates (transport.codec rpc family): versioned
        # per-client shadow copies of the shards this server sent, so a
        # delta UPDATE folds back into a full tree before aggregation
        from split_learning_tpu.runtime.codec import parse_codec_map
        self._delta_shadow = None
        if parse_codec_map(getattr(cfg.transport, "codec",
                                   None)).get("rpc") is not None:
            from split_learning_tpu.runtime.codec.delta import DeltaShadow
            self._delta_shadow = DeltaShadow(faults=self.faults)
        if self.fleet is not None:
            # a `lost` client's delta shadow is a full shard copy
            # pinned in host memory; before this hook only the elastic
            # prune reclaimed it — a lost-but-never-pruned client (or
            # a non-elastic deployment) leaked its shadow forever
            self.fleet.on_lost = self._on_client_lost
        # streaming aggregation plane (runtime/aggregate.py, ROADMAP
        # item 4): fold each UPDATE into a running per-stage weighted
        # sum the moment it decodes, so the UPDATE barrier holds O(1)
        # parameter trees instead of O(clients).  Only strategies whose
        # aggregation consumes the whole update list at once stream;
        # the others (relay/periodic/fedasync read individual
        # u.params) keep barrier semantics untouched.
        self._agg = cfg.aggregation
        self._streaming = (self._agg.streaming and self._agg.strategy
                           in agg_plane.FOLD_STRATEGIES)
        self._fold_backend = (agg_plane.make_fold_backend(cfg)
                              if self._streaming else None)
        self._fold: agg_plane.StreamingFold | None = None
        self._group_of: dict = {}      # client_id -> AggGroup (tree on)
        self._l1: list = []            # this invocation's L1Aggregators
        self._l1_fallback: dict = {}   # group idx -> fallback drain state
        # multi-process aggregator tree (aggregation.remote,
        # runtime/aggnode.py): adopted node registry (AggHello /
        # spawned Popen handles), the current invocation's node ->
        # groups assignment, nodes already declared dead this
        # invocation, and the full tree plan by group idx
        self._agg_nodes: dict = {}     # node_id -> {t, proc?}
        # cross-host MPMD stage pipeline (pipeline.remote,
        # runtime/stagehost.py): adopted stage-host registry (StageHello
        # / spawned Popen handles) and the standing host -> later-stage
        # client-slot assignment.  _stage_watch arms the barrier-side
        # death check only INSIDE a train_cluster attempt — a host dying
        # between rounds is handled by the next attempt's recovery, not
        # by an exception out of an idle pump.
        self._stage_hosts: dict = {}        # host_id -> {t, proc?, dead?}
        self._stage_assignments: dict = {}  # host_id -> [slot dicts]
        self._stage_watch = False
        self._l1_remote: dict = {}     # node_id -> [AggGroup]
        self._dead_nodes: set = set()
        self._tree_groups: dict = {}   # group idx -> AggGroup
        self._tree_roots: list = []    # parentless groups (root children)
        self._tree_narrowed: dict = {}   # group idx -> responsive members
        self._cur_cluster = 0
        self._agg_topology: dict | None = None   # /fleet view
        # partial-sum codec (transport.codec: partial): the spec, and
        # the per-stage START-base trees the delta mode reconstructs
        # against (both endpoints hold the generation's base)
        from split_learning_tpu.runtime.codec import parse_codec_map
        self._partial_codec = parse_codec_map(
            getattr(cfg.transport, "codec", None)).get("partial")
        self._partial_bases: dict = {}
        self._partial_base_gen: int | None = None
        # members of a dead L1's group whose UPDATE frames the L1
        # consumed before dying — unrecoverable, so the UPDATE barrier
        # stops waiting for them (counted agg_fallback_abandons)
        self._agg_gone: set = set()
        self._l1_logs: dict = {}       # agg_id -> cached Logger (the
        # L1's [<<<]/[>>>] markers carry the aggregator participant
        # name, so --validate-log replays the AGGREGATOR_FSM on real
        # runs instead of vacuously)
        # FedAvgM velocity, keyed cluster_id -> {path: vel}: each
        # cluster's fold is its own optimizer stream — a shared dict
        # would feed cluster B the velocity cluster A wrote THIS round
        self._agg_velocity: dict = {}
        # elastic membership (topology.elastic-join): ids the CURRENT
        # plans were computed from; per-ROUND alive/silent bookkeeping
        # (sequential strategies run several train_cluster invocations
        # per round — a slow client must not accrue several misses in
        # one round); consecutive missed ROUNDS per client (a fresh
        # REGISTER forgives); clients whose next START must carry
        # params whatever the strategy's wire economy says (joiners,
        # and everyone after a re-plan moved the cuts)
        self._planned_ids: set = set()
        self._round_alive: set = set()
        self._round_silent: set = set()
        self._missed: dict[str, int] = {}
        self._needs_params: set = set()
        self._replan_failed_for: set | None = None
        # fence: messages are stamped with a per-train_cluster-invocation
        # generation (NOT the round index — sequential strategies run
        # several invocations with the same round_idx, and a straggler
        # from sub-call k must not satisfy sub-call k+1's barriers)
        self._gen = 0
        self._cur_gen = 0
        # async decoupled mode (learning.mode: async): the generation IS
        # the global model version.  Instead of the hard gen fence, the
        # UPDATE pump admits contributions through a bounded-staleness
        # window (_admit_update) and the UPDATE barrier cuts a new
        # version at learning.async-quorum fresh contributions.
        self._async = cfg.learning.mode == "async"
        # (client_id, version) pairs already folded — the dedup that
        # keeps an at-least-once redelivery of a post-fold Update from
        # double-counting samples (and a stale resend from re-folding
        # across invocations); pruned past the admission window
        self._folded_versions: set = set()
        # late-READY SYN: True between the SYN fan-out and the end of
        # an async invocation, so a straggler's late READY still gets
        # its SYN instead of idling out the whole round
        self._syn_live = False
        self._syn_round = 0
        # per-client responsive-set fence overrides captured at the
        # SYN fan-out, reused for late-READY joiners
        self._syn_overrides: dict = {}
        # closed-loop resource-aware scheduler (runtime/scheduler.py,
        # scheduler.enabled): round-boundary decision loop consuming
        # the fleet-telemetry plane — online clustering, straggler
        # demotion/eviction with per-client knob retunes, measured-
        # throughput cut re-planning.  _sched_gone mirrors _agg_gone:
        # clients a barrier stopped waiting for by scheduler policy
        # (mid-round drop), reset per invocation; _stage_of maps the
        # invocation's active clients to stages so a mid-round drop
        # can release the streaming fold's reorder window.
        self.scheduler = None
        self._sched_gone: set = set()
        self._sched_watched: set = set()
        self._stage_of: dict = {}
        sch = getattr(cfg, "scheduler", None)
        if sch is not None and sch.enabled:
            from split_learning_tpu.runtime.scheduler import Scheduler
            self.scheduler = Scheduler(cfg, log=self.log,
                                       faults=self.faults,
                                       gauges=self.gauges)
        # flight-recorder fleet snapshots (runtime/blackbox.py): one
        # BlackboxDump fan-out per distinct dead participant, globally
        # rate-limited so a death CASCADE yields one snapshot naming
        # the first victim instead of a dump storm
        self._bb_snapped: set = set()
        self._bb_last_snap = 0.0

    # -- rpc pump ------------------------------------------------------------

    def _pump_one(self, timeout: float) -> bool:
        raw = self.bus.get(RPC_QUEUE, timeout=timeout)
        if raw is None:
            if self.fleet is not None:
                # liveness ages are only trustworthy at a DRAINED
                # queue: after an unpumped phase (validation) the
                # backlog still holds everyone's beats, and opening
                # the gate on the first frame would flash spurious
                # `lost` states before the drain finishes — see
                # FleetMonitor.note_pump
                self.fleet.note_pump()
            # drained pump = a quiet moment: the right time for the
            # (throttled) digest-node death check, so a dead node's
            # clients are re-pointed within _DIGEST_CHECK_S whatever
            # phase the round is in
            self._check_digest_nodes()
            return False
        t_wall = time.time()
        t0 = time.perf_counter()
        try:
            msg = self._assembler.feed(raw)
        except Exception as e:  # noqa: BLE001 — corrupt frame: a flipped
            # bit on rpc_queue must cost one message, not the server
            self.faults.inc("corrupt_rejected")
            self.log.warning(f"dropping undecodable rpc frame: {e}")
            self.wire.add_decode(time.perf_counter() - t0)
            return True
        dt = time.perf_counter() - t0
        self.wire.add_decode(dt)
        self.hists.observe("decode", dt)
        if msg is None:
            return True   # chunk of a still-partial frame
        ctx = unpack_ctx(getattr(msg, "_ctx", None))
        if ctx is not None:
            # consume span linked to the client's publish span: the
            # UPDATE upload gets a flow edge like any data-plane frame
            _, sender_span, t_send = ctx
            rtt = max(0.0, t_wall - t_send)
            self.hists.observe("frame_rtt", rtt)
            self.tracer.record(
                "consume", t_wall, t_wall + dt, parent=sender_span,
                queue=RPC_QUEUE, kind=type(msg).__name__,
                nbytes=len(raw), rtt_ms=round(rtt * 1e3, 3),
                round=getattr(msg, "round_idx", None))
        if isinstance(msg, Heartbeat):
            # liveness + telemetry only — never logged (one frame per
            # interval per client would drown the protocol trace).
            # note_heartbeat applies the seq/send-time staleness guard,
            # so a duplicated/reordered beat can't resurrect a lost
            # client or extend its liveness.
            if self.fleet is not None:
                self.fleet.note_heartbeat(
                    msg.client_id, msg.telemetry,
                    via=self._digest_route.get(msg.client_id))
            return True
        if isinstance(msg, FleetDigest):
            # one aggregator node's rolled-up heartbeat summary
            # (observability.digest-interval) — the O(nodes) ingest
            # that replaces O(clients) individual beats.  note_digest
            # applies the same (t, seq) staleness guard as heartbeats
            # (duplicates counted stale_digests), and the frame itself
            # proves the node's process is alive.  A digest from a
            # node already failed over is stale BY DEFINITION: its
            # clients were re-pointed to direct heartbeats, and
            # re-installing the standing digest (a reordered frame
            # published before the death) would double-count them
            # forever — _check_digest_nodes never revisits dead nodes.
            if self.fleet is not None:
                if msg.node_id in self._digest_dead:
                    self.faults.inc("stale_digests")
                else:
                    self.fleet.note_frame(msg.node_id)
                    self.fleet.note_digest(msg.node_id, msg.digest)
            return True
        if self.fleet is not None:
            cid = getattr(msg, "client_id", None)
            if cid is not None:
                # any rpc frame proves a live process (clients with
                # heartbeats disabled still register liveness); the
                # piggybacked Update snapshot counts as a full beat —
                # consumed even when the Update itself is stale-gen,
                # liveness is not round-fenced
                if isinstance(msg, Update) and msg.telemetry:
                    self.fleet.note_heartbeat(
                        cid, msg.telemetry,
                        via=self._digest_route.get(cid))
                else:
                    self.fleet.note_frame(
                        cid, via=self._digest_route.get(cid))
        if isinstance(msg, Register):
            if (self.cfg.topology.elastic_join
                    and not 1 <= msg.stage <= self.cfg.num_stages):
                # elastic: a stored out-of-range registration would
                # poison every later re-planning pass, so drop it.
                # Non-elastic keeps the old fail-fast: it counts toward
                # the barrier and planning immediately raises naming
                # the misconfigured client.
                self.log.warning(
                    f"ignoring REGISTER {msg.client_id}: stage "
                    f"{msg.stage} outside 1..{self.cfg.num_stages}")
                return True
            # keyed by client_id: clients re-REGISTER until STARTed (the
            # server's startup purge may race a fast client's first one)
            if msg.client_id not in self._registrations:
                self.log.received(f"REGISTER {msg.client_id} "
                                  f"stage={msg.stage}")
            self._registrations[msg.client_id] = Registration(
                client_id=msg.client_id, stage=msg.stage,
                cluster=msg.cluster, profile=msg.profile)
            # a REGISTER proves a live process: forgive barrier misses
            # (a crashed-and-restarted client re-joins by re-registering)
            self._missed.pop(msg.client_id, None)
        elif isinstance(msg, Ready):
            # fenced like Notify/Update: a late READY from a dropped
            # invocation must not let the server SYN a client that is
            # still unwinding the old round
            if msg.round_idx != self._cur_gen:
                self.log.warning(f"stale READY {msg.client_id} "
                                 f"gen={msg.round_idx} (dropped)")
            else:
                late = (self._syn_live
                        and msg.client_id not in self._ready)
                self._ready.add(msg.client_id)
                if late:
                    # async pipelining: the SYN fan-out already went
                    # out (the READY barrier collapsed to the
                    # responsive set) — a straggler that finishes its
                    # previous round's late upload and re-READYs still
                    # joins THIS round instead of idling to the next.
                    # It gets the same responsive-set fence overrides
                    # the fan-out carried: the static START values may
                    # name feeders dropped at the barrier, whose
                    # fences would stall its strict drain / burn the
                    # async drain grace every round.
                    q, feeders = self._syn_overrides.get(
                        msg.client_id, (None, None))
                    self.bus.publish(
                        reply_queue(msg.client_id),
                        encode(Syn(self._syn_round,
                                   sda_fence_quorum=q,
                                   sda_feeders=feeders)))
                    self.log.sent(f"SYN -> {msg.client_id} "
                                  "(late READY)")
        elif isinstance(msg, Notify):
            if msg.round_idx != self._cur_gen:
                self.log.warning(f"stale NOTIFY {msg.client_id} "
                                 f"gen={msg.round_idx} (dropped)")
            else:
                self._notified.add(msg.client_id)
                self.log.received(f"NOTIFY {msg.client_id}")
        elif isinstance(msg, Update):
            # generation fence (sync) / bounded-staleness admission
            # window (async) + (client_id, version) dedup — one door
            # for every fold-bound Update
            self._admit_update(msg)
        elif isinstance(msg, PartialAggregate):
            # one aggregator's folded group landing at the root
            if msg.round_idx != self._cur_gen:
                self.faults.inc("agg_stale_drops")
                self.log.warning(
                    f"stale PARTIALAGGREGATE {msg.aggregator_id} "
                    f"gen={msg.round_idx} (dropped)")
            else:
                self._fold_partial(msg, nbytes=self._assembler.last_bytes)
        elif isinstance(msg, AggHello):
            # a standalone aggregator process offering itself for
            # adoption (aggregation.remote); liveness afterwards rides
            # its heartbeats through the FleetMonitor like a client's
            ent = self._agg_nodes.setdefault(msg.node_id, {})
            if "t" not in ent:
                self.log.received(f"AGGHELLO {msg.node_id}")
            ent["t"] = time.time()
            if self.fleet is not None:
                self.fleet.note_frame(msg.node_id)
        elif isinstance(msg, StageHello):
            # a standalone stage-host process offering itself for
            # adoption (pipeline.remote); liveness afterwards rides its
            # heartbeats through the FleetMonitor like a client's.  A
            # host helloing again AFTER assignment (slow adoption ack,
            # or a restarted process under the same id) gets its
            # standing slots re-sent — the host side is idempotent.
            ent = self._stage_hosts.setdefault(msg.host_id, {})
            if "t" not in ent:
                self.log.received(f"STAGEHELLO {msg.host_id}")
            ent["t"] = time.time()
            if self.fleet is not None:
                self.fleet.note_frame(msg.host_id)
            if self._stage_assignments.get(msg.host_id):
                self._send_stage_assign(msg.host_id)
        return True

    def _admit_update(self, msg: Update) -> None:
        """The one admission door for client Updates.

        * dedup first: a resent (at-least-once redelivered) Update for
          a ``(client_id, version)`` already folded is dropped BEFORE
          any sample accounting — the weight-less skip path in
          ``aggregate_cluster`` must never see the same contribution
          twice (PR 6 double-count fix);
        * sync (``learning.mode: sync``): only the current generation
          folds — the hard fence, unchanged semantics;
        * async: an Update seeded from version ``v`` is admitted while
          ``server_version - v <= learning.max-staleness`` and folded
          with weight scaled by ``staleness-decay ** lag`` under a
          ``client@vN`` extras key (a straggler contributes late
          instead of stalling the fleet); anything older is
          rejected-and-counted (``agg_stale_updates``).
        """
        lrn = self.cfg.learning
        ver = msg.version if msg.version is not None else msg.round_idx
        key = (msg.client_id, ver)
        if key in self._folded_versions:
            self.faults.inc("agg_dup_drops")
            self.log.warning(f"duplicate UPDATE {msg.client_id} "
                             f"v{ver} (already folded; dropped)")
            return
        lag = self._cur_gen - ver
        if lag == 0 and msg.round_idx == self._cur_gen:
            self._fold_update(msg)
            if self._fold is not None:
                # streaming fold: the weights fold into the running
                # sum NOW (a shallow copy keeps the tree alive in
                # the fold's reorder window) and the barrier list
                # holds a weight-stripped record — O(1) full trees
                # at the UPDATE barrier instead of O(clients)
                self._fold.add_update(copy.copy(msg))
                msg.params = None
                msg.batch_stats = None
            self._folded_versions.add(key)
            self._updates.append(msg)
            if self._async and self.fleet is not None:
                # version lag is an async-mode signal: in sync mode the
                # generation bumps per INVOCATION (sequential clusters
                # would read as phantom lag and flap the straggler state)
                self.fleet.note_client_version(msg.client_id, ver)
            self.log.received(f"UPDATE {msg.client_id} "
                              f"samples={msg.num_samples} ok={msg.ok}")
            return
        # per-client staleness window: a scheduler-demoted compute-slow
        # client folds through a WIDER admission window than the global
        # config grants (runtime/scheduler.py _act_demote)
        max_st = lrn.max_staleness
        if self.scheduler is not None:
            max_st += self.scheduler.staleness_bonus_for(msg.client_id)
        if (self._async and self._fold is not None
                and 0 < lag <= max_st):
            # bounded-staleness admission: fold with decayed weight,
            # keyed off the canonical window so the same client's
            # FRESH contribution this round still occupies its slot
            self._fold_update(msg)
            scale = lrn.staleness_decay ** lag
            self._fold.add_update(copy.copy(msg), scale=scale,
                                  key=f"{msg.client_id}@v{ver}")
            msg.params = None
            msg.batch_stats = None
            self._folded_versions.add(key)
            self._updates.append(msg)
            self.faults.inc("agg_stale_admits")
            if self.fleet is not None:   # stale admits only exist async
                self.fleet.note_client_version(msg.client_id, ver)
            self.log.received(
                f"UPDATE {msg.client_id} v{ver} lag={lag} "
                f"(stale-admitted, weight x{scale:g})")
            return
        self.faults.inc("agg_stale_updates")
        self.log.warning(f"stale UPDATE {msg.client_id} v{ver} "
                         f"lag={lag} (rejected)")

    def _fold_update(self, msg: Update) -> None:
        """Reconstruct a delta-encoded UPDATE in place (``base +
        dequant(delta)`` against the versioned shadow).  When the
        version chain is broken (shadow missing/moved — redelivery
        gap, server state loss) the delta is unusable: the update is
        kept WEIGHT-LESS (the barrier must not stall on it; aggregation
        skips param-less updates) and the client is marked for a full
        re-seed, so the next round repairs the chain.  Full frames
        (delta_base None) pass through and are counted — they ARE the
        resync path."""
        if msg.delta_base is None:
            if self._delta_shadow is not None and msg.params is not None:
                self.faults.inc("delta_full_frames")
            return
        full = (None if self._delta_shadow is None
                else self._delta_shadow.fold(msg.client_id,
                                             msg.delta_base, msg.params))
        if full is None:
            self.log.warning(
                f"delta UPDATE {msg.client_id} against unknown base "
                f"v{msg.delta_base}: weights dropped; full-frame "
                "resync next round")
            self._needs_params.add(msg.client_id)
            msg.params = None
            msg.batch_stats = None
        else:
            msg.params = full
        msg.delta_base = None   # downstream sees a plain (full) update

    def _on_client_lost(self, cid: str) -> None:
        """FleetMonitor ``lost`` transition hook: reclaim the client's
        delta shadow (a full shard copy pinned in host memory).  A
        rejoiner full-frames its next UPDATE anyway — the chain repairs
        itself, only the memory was leaking."""
        if self._delta_shadow is not None:
            self._delta_shadow.clear(cid)
            self.gauges.set("agg_shadow_bytes",
                            self._delta_shadow.nbytes())
        # the FleetMonitor tracks every heartbeating participant, not
        # just clients — name the role the postmortem should report
        role = ("agg_node" if cid in self._agg_nodes
                else "stage_host" if cid in self._stage_hosts
                else "client")
        self._fleet_snapshot(cid, role, "participant_lost")

    # -- flight-recorder fleet snapshot (runtime/blackbox.py) ----------------

    #: minimum wall-clock gap between fleet snapshots: a cascade of
    #: deaths (one kill tipping over its dependents) produces ONE
    #: snapshot naming the first victim — the proximate cause the
    #: postmortem wants — instead of a dump storm
    BB_SNAPSHOT_MIN_S = 5.0

    def _death_kind(self, victim: str, registry: dict) -> str:
        """``child_exit`` when the victim is a subprocess this server
        spawned and its Popen handle reports an exit code, else
        ``participant_lost`` (heartbeats aged out — externally-started
        process, or a SIGKILL that left no exit notification)."""
        proc = (registry.get(victim) or {}).get("proc")
        if proc is not None and proc.poll() is not None:
            return "child_exit"
        return "participant_lost"

    def _fleet_snapshot(self, victim: str, role: str,
                        kind: str) -> None:
        """Record a participant death in the server's ring and trigger
        the fleet-wide flight-recorder snapshot: dump the server's own
        ring, fan a :class:`BlackboxDump` out to every surviving
        participant's reply queue, and sweep the broker shards' rings
        over their control queues — so the postmortem assembler finds
        every process's last seconds in one artifacts directory even
        though the victim itself (SIGKILL) wrote nothing."""
        if not blackbox.enabled():
            return
        blackbox.record(kind, participant=victim, role=role,
                        round=int(getattr(self, "_cur_round", 0)),
                        gen=self._cur_gen)
        now = time.monotonic()
        if victim in self._bb_snapped \
                or now - self._bb_last_snap < self.BB_SNAPSHOT_MIN_S:
            return
        self._bb_snapped.add(victim)
        self._bb_last_snap = now
        reason = f"{kind}:{victim}"
        # own ring FIRST — it holds the death event this snapshot is
        # named after, and a fan-out failure must not lose it
        blackbox.dump(reason)
        targets = (set(self._registrations) | set(self._agg_nodes)
                   | set(self._stage_hosts))
        targets.discard(victim)
        for pid in sorted(targets):
            if self.fleet is not None \
                    and self.fleet.state(pid) == "lost":
                continue   # its queue has no consumer; skip, don't park
            try:
                self.bus.publish(reply_queue(pid), encode(BlackboxDump(
                    participant=pid, reason=reason,
                    t_req=time.time())))  # slcheck: wire=BlackboxDump
            except Exception:  # noqa: BLE001 — snapshot is best-effort
                blackbox.record("error", where="bb_fanout",
                                participant=pid)
        self.log.warning(f"flight-recorder fleet snapshot: {reason} "
                         f"({len(targets)} participants asked to dump)")
        if self.cfg.transport.kind == "tcp":
            # shard sweep dials TCP: off-pump so barrier latency stays
            # flat while the shards answer
            threading.Thread(target=self._sweep_broker_blackbox,
                             args=(reason,), daemon=True,
                             name="bb-sweep").start()

    def _sweep_broker_blackbox(self, reason: str) -> None:
        """Pull each broker shard's ring over ``__broker__.blackbox``
        and persist it next to this server's own dumps (the shard
        replies with bytes; the REQUESTER owns the dump directory)."""
        from split_learning_tpu.runtime.bus import broker_blackbox
        host, port = self.cfg.transport.host, self.cfg.transport.port
        for i in range(self.cfg.broker.shards):
            try:
                d = broker_blackbox(host, port + i, timeout=2.0)
            except Exception:  # noqa: BLE001 — dead/foreign shard
                blackbox.record("error", where="bb_broker_sweep",
                                shard=i)
                continue
            d.setdefault("snap_reason", reason)
            d.setdefault("participant", f"broker-shard_{i}")
            blackbox.write_dump_dict(d)

    def _fold_partial(self, msg: PartialAggregate,
                      nbytes: int = 0) -> None:
        """Fold one PartialAggregate at its group's canonical position
        and book its members: each one gets a weight-less Update record
        (barrier membership, ok flag, elastic liveness) and its
        piggybacked telemetry feeds the fleet monitor — clients behind
        an aggregator stay individually visible everywhere but the
        fold.  A codec'd payload (transport.codec: partial) is
        reconstructed to f32 sums first; one that cannot be (missing
        delta base) is dropped and counted — the fallback machinery,
        not a silently wrong fold, owns that group's fate."""
        if self._fold is None:
            self.log.warning(
                f"PARTIALAGGREGATE {msg.aggregator_id} outside a "
                "streaming invocation (dropped)")
            return
        self._agg_ingress_bytes = (
            getattr(self, "_agg_ingress_bytes", 0) + int(nbytes))
        if msg.codec or msg.members_z:
            from split_learning_tpu.runtime.codec.partial import (
                PartialCodecError, decode_partial_msg,
            )
            try:
                decode_partial_msg(msg, bases=self._partial_bases,
                                   base_gen=self._partial_base_gen)
            except PartialCodecError as e:
                self.faults.inc("partial_codec_errors")
                self.log.warning(
                    f"PARTIALAGGREGATE {msg.aggregator_id}: "
                    f"undecodable codec'd payload ({e}); dropped")
                return
        # gen-fenced upstream (the pump drops stale PartialAggregates
        # before this); tree members are never stale-admitted
        self._fold.add_partial(  # slcheck: async-exempt
            msg.stage, agg_plane.group_key(msg.group), msg.sums,
            msg.weight, msg.dtypes, stat_sums=msg.stat_sums,
            stat_weight=msg.stat_weight, stat_dtypes=msg.stat_dtypes,
            n_samples=msg.n_samples)
        for m in msg.members or []:
            cid = m.get("client_id")
            if cid is None:
                continue
            if self.fleet is not None and m.get("telemetry"):
                self.fleet.note_heartbeat(
                    cid, m["telemetry"],
                    via=self._digest_route.get(cid))
            # num_samples=0: the group's stage-1 samples already rode
            # the partial's n_samples — a per-member recount would
            # double the round total
            self._updates.append(Update(
                client_id=cid, stage=int(m.get("stage", msg.stage)),
                cluster=msg.cluster, params=None, num_samples=0,
                ok=bool(m.get("ok", True)), round_idx=msg.round_idx))
        self.log.received(
            f"PARTIALAGGREGATE {msg.aggregator_id} "
            f"members={len(msg.members or [])} weight={msg.weight:g}")

    def _node_dead(self, node_id: str) -> bool:
        """A remote aggregator node is dead when its spawned process
        exited or the FleetMonitor marked it ``lost`` (no heartbeat
        within observability.liveness-timeout) — the satellite fix for
        the thread-liveness assumption: ``_poll_l1`` used to detect a
        dead L1 via ``Thread.is_alive``, which a remote process has no
        equivalent of."""
        ent = self._agg_nodes.get(node_id) or {}
        proc = ent.get("proc")
        if proc is not None and proc.poll() is not None:
            return True
        return (self.fleet is not None
                and self.fleet.state(node_id) == "lost")

    # -- cross-host MPMD stage pipeline (pipeline.remote) --------------------

    def _host_dead(self, host_id: str) -> bool:
        """Same liveness rule as :meth:`_node_dead`, for stage hosts:
        the spawned child exited, OR the FleetMonitor aged the host's
        heartbeats to ``lost`` (externally-started hosts have no Popen
        handle — the telemetry plane is their only death signal)."""
        ent = self._stage_hosts.get(host_id) or {}
        if ent.get("dead"):
            return True
        proc = ent.get("proc")
        if proc is not None and proc.poll() is not None:
            return True
        return (self.fleet is not None
                and self.fleet.state(host_id) == "lost")

    def _send_stage_assign(self, host_id: str) -> None:
        self.bus.publish(reply_queue(host_id), encode(StageAssign(
            host_id=host_id, gen=self._cur_gen,
            round_idx=getattr(self, "_cur_round", 0),
            slots=[dict(s) for s in
                   self._stage_assignments.get(host_id, [])])))
        self.log.sent(
            f"STAGEASSIGN {host_id} "
            f"slots={len(self._stage_assignments.get(host_id, []))}")

    def assign_stage_slots(self) -> None:
        """Deal the pipeline's later-stage client slots round-robin
        across the adopted stage hosts and publish each host its
        StageAssign.  Runs BEFORE the registration barrier: the slots'
        inner clients ARE later-stage registrations, so the barrier
        cannot complete until the hosts have spun them up."""
        from split_learning_tpu.runtime.plan import pipeline_slots
        slots = pipeline_slots(self.cfg)
        hosts = [h for h in sorted(self._stage_hosts)
                 if "t" in self._stage_hosts[h]
                 and not self._host_dead(h)]
        if not hosts:
            self.log.warning(
                "pipeline.remote: no stage host adopted — "
                "later-stage slots unassigned")
            return
        self._stage_assignments = {h: [] for h in hosts}
        for j, slot in enumerate(slots):
            self._stage_assignments[hosts[j % len(hosts)]].append(slot)
        for h in hosts:
            self._send_stage_assign(h)

    def _check_stage_hosts(self) -> None:
        """Barrier-side death check (armed only inside a round
        attempt): the first assigned host found dead aborts the attempt
        via :class:`_StageHostLost` — the retry wrapper re-assigns and
        re-runs rather than letting the barrier eat its full deadline
        waiting for clients whose process is gone."""
        for host_id in sorted(self._stage_assignments):
            if self._stage_assignments[host_id] \
                    and self._host_dead(host_id):
                raise _StageHostLost(host_id)

    def _recover_stage_host(self, host_id: str) -> None:
        """Counted re-assignment after a stage-host death: the dead
        host's slots move to the surviving hosts round-robin UNDER THE
        SAME CLIENT IDS (the per-client ShardRunner seed is a client-id
        hash, so the re-run round's fold stays bit-identical to the
        fault-free twin), and each touched survivor gets a fresh
        StageAssign.  One ``stage_host_deaths`` per death, one
        ``stage_reassigns`` per moved slot — the chaos cell's exact
        expected counts."""
        self.faults.inc("stage_host_deaths")
        self._fleet_snapshot(host_id, "stage_host",
                             self._death_kind(host_id,
                                              self._stage_hosts))
        ent = self._stage_hosts.setdefault(host_id, {})
        ent["dead"] = True
        dead_slots = self._stage_assignments.pop(host_id, [])
        survivors = [h for h in sorted(self._stage_assignments)
                     if not self._host_dead(h)]
        if not survivors:
            raise RoundTimeout(
                f"stage host {host_id} died and no live stage host "
                "remains to adopt its "
                f"{len(dead_slots)} slot(s)")
        touched = set()
        for j, slot in enumerate(dead_slots):
            tgt = survivors[j % len(survivors)]
            self._stage_assignments[tgt].append(slot)
            self.faults.inc("stage_reassigns")
            touched.add(tgt)
        self.log.warning(
            f"stage host {host_id} lost: re-assigned "
            f"{len(dead_slots)} slot(s) to {sorted(touched)}")
        for tgt in sorted(touched):
            self._send_stage_assign(tgt)

    # -- hierarchical heartbeat roll-up (observability.digest-interval) ------

    #: wall-clock cadence of the digest-node death check (cheap: a
    #: dict walk over the routed nodes, work only on a death)
    _DIGEST_CHECK_S = 0.5

    def _digest_route_for(self, cid: str) -> str | None:
        """The digest queue this client's heartbeats should roll up
        through (START ``extra.digest``), or None for direct rpc
        beats.  Assignment is a stable hash over the live adopted
        nodes, so successive STARTs keep a client on the same node
        (its node-local state machine keeps its history)."""
        if self._digest_interval <= 0 or self.fleet is None:
            return None
        nodes = [n for n in sorted(self._agg_nodes)
                 if n not in self._digest_dead
                 and not self._node_dead(n)]
        if not nodes:
            return None
        nid = nodes[zlib.crc32(cid.encode()) % len(nodes)]
        self._digest_route[cid] = nid
        # any standing direct entry stops aging at this monitor — the
        # node's state machine covers the client from here (its next
        # beats land on the node's queue, not ours)
        self.fleet.route_via(cid, nid)
        return digest_queue(nid)

    def _check_digest_nodes(self, now: float | None = None) -> None:
        """Digest-node death fallback: a routed node whose process
        exited (or whose own heartbeats went FleetMonitor-``lost``)
        gets its digest queue drained HERE — the heartbeats parked
        there are liveness proof, not losses — and each of its clients
        is re-pointed to direct rpc beats with a counted
        ``digest_fallbacks``.  The node's standing digest is dropped
        from the fold (its clients now count via their own beats), so
        the degradation can neither double-count nor mint a phantom
        ``lost``."""
        if not self._digest_route and not self._dead_drains:
            return
        now = time.monotonic() if now is None else now
        if now - self._digest_check_t < self._DIGEST_CHECK_S:
            return
        self._digest_check_t = now
        if self.fleet is not None:
            # _node_dead reads the monitor's state: advance first so a
            # silent node's `lost` is current at this check, not the
            # last barrier's
            self.fleet.advance()
        # keep draining dead nodes' queues: a client mid-compile only
        # reads its reply queue (the DigestRoute) at the next control
        # point, so its beats keep landing on the dead queue for a
        # while — every one of them is liveness proof this monitor
        # must see, or the fallback itself would mint the phantom
        # `lost` it exists to prevent.  Quiesces to one empty
        # zero-timeout get per dead node per check.
        for nid, asm in self._dead_drains.items():
            self._drain_dead_queue(nid, asm)
        routed: dict[str, list] = {}
        for cid, nid in self._digest_route.items():
            routed.setdefault(nid, []).append(cid)
        for nid, cids in routed.items():
            if nid in self._digest_dead or not self._node_dead(nid):
                continue
            self._digest_dead.add(nid)
            asm = self._dead_drains[nid] = FrameAssembler(
                faults=self.faults)
            drained = self._drain_dead_queue(nid, asm)
            if self.fleet is not None:
                self.fleet.drop_digest(nid)
            for cid in sorted(cids):
                self.faults.inc("digest_fallbacks")
                self._digest_route.pop(cid, None)
                self.bus.publish(
                    reply_queue(cid),
                    encode(DigestRoute(client_id=cid, queue=None)))  # slcheck: wire=DigestRoute
                if self.fleet is not None:
                    # the client was beating into the dead node's
                    # queue, not lying silent — a fresh liveness grace
                    # covers the re-route gap
                    self.fleet.note_frame(cid)
            self.log.warning(
                f"digest node {nid} is dead; re-pointed {len(cids)} "
                f"client(s) to direct heartbeats ({drained} queued "
                "beat(s) recovered)")

    def _drain_dead_queue(self, nid: str, asm: FrameAssembler) -> int:
        """Fold the heartbeats parked on a dead digest node's queue
        straight into this monitor (via=None: the senders are falling
        back to direct reporting)."""
        drained = 0
        q = digest_queue(nid)
        while True:
            raw = self.bus.get(q, timeout=0.0)
            if raw is None:
                return drained
            try:
                m = asm.feed(raw)
            except Exception:  # noqa: BLE001 — one corrupt beat
                self.faults.inc("corrupt_rejected")
                continue
            if isinstance(m, Heartbeat) and self.fleet is not None:
                self.fleet.note_heartbeat(m.client_id, m.telemetry)
                drained += 1

    def _spawn_l1_threads(self, plan, groups, narrowed: dict) -> None:
        """Thread-mode aggregators (the default): one L1Aggregator
        thread per group, any level.  Over TCP each gets its own
        transport stack (a blocked get serializes a TcpTransport's
        socket); in-proc they share the bus."""
        l1_deadline = time.monotonic() + self.client_timeout
        for g in groups:
            agg_id = f"aggregator_{plan.cluster_id}_{g.idx}"
            l1_bus, owns = self.bus, False
            if self.cfg.transport.kind == "tcp":
                from split_learning_tpu.runtime.chaos import (
                    make_runtime_transport,
                )
                l1_bus = make_runtime_transport(
                    self.cfg, agg_id, faults=self.faults)
                owns = True
            l1_log = self._l1_logs.get(agg_id)
            if l1_log is None:
                l1_log = self._l1_logs[agg_id] = Logger.for_run(
                    self.cfg, agg_id, console=False)
            out_q = (RPC_QUEUE if g.parent is None
                     else agg_plane.aggregate_queue(plan.cluster_id,
                                                    g.parent))
            t = agg_plane.L1Aggregator(
                l1_bus, cluster=plan.cluster_id, group=g,
                members=narrowed[g.idx], gen=self._cur_gen,
                deadline=l1_deadline, log=l1_log,
                faults=self.faults,
                chunk_bytes=self.cfg.transport.chunk_mb << 20,
                owns_bus=owns, out_queue=out_q,
                codec=self._partial_codec,
                base=self._partial_bases.get(g.stage),
                base_gen=self._partial_base_gen)
            t.start()
            self._l1.append(t)

    def _dispatch_remote(self, plan, groups, narrowed: dict,
                         node_ids: list, round_idx: int) -> None:
        """Assign the tree's groups round-robin across the adopted
        aggregator processes and send each node ONE AggAssign naming
        its groups (and the delta-codec base trees, when configured).
        The node folds exactly what a thread-mode L1 would — same
        L1Aggregator objects, same queues — so the choreography and
        determinism contracts carry over unchanged."""
        codec_s = None
        if self._partial_codec is not None:
            from split_learning_tpu.runtime.codec.partial import (
                spec_string,
            )
            codec_s = spec_string(self._partial_codec)
        self._l1_remote = {nid: [] for nid in node_ids}
        ordered = sorted(groups, key=lambda g: (g.level, g.idx))
        for i, g in enumerate(ordered):
            self._l1_remote[node_ids[i % len(node_ids)]].append(g)
        for nid, glist in self._l1_remote.items():
            wire_groups = []
            for g in glist:
                d = g.as_dict()
                d["members"] = list(narrowed[g.idx])
                wire_groups.append(d)
            assign = AggAssign(
                node_id=nid, cluster=plan.cluster_id,
                gen=self._cur_gen, round_idx=round_idx,
                groups=wire_groups, deadline_s=self.client_timeout,
                codec=codec_s,
                bases=(dict(self._partial_bases)
                       if self._partial_bases else None),
                chunk_bytes=self.cfg.transport.chunk_mb << 20)
            for part in encode_parts(
                    assign, self.cfg.transport.chunk_mb << 20):
                self.bus.publish(reply_queue(nid), part)  # slcheck: wire=AggAssign
            self.log.sent(f"AGGASSIGN -> {nid} "
                          f"groups={len(wire_groups)}")

    #: liveness grace on a fallback drain: a dead L1 may have consumed
    #: a member's UPDATE frames before dying — those are unrecoverable,
    #: and the member (already in its post-round wait) will never
    #: resend, so the barrier must not wait client_timeout for it.
    #: The clock resets on every recovered frame, so an actively
    #: draining queue never expires; only a drained-and-silent one
    #: abandons its missing members (same bound as _finish_l1).
    L1_FALLBACK_GRACE_S = 30.0

    def _poll_l1(self) -> None:
        """Aggregator-tree health check, run every UPDATE-barrier pump
        iteration: a dead aggregator — a thread that is no longer
        alive, or a REMOTE node whose spawned process exited or whose
        heartbeats went FleetMonitor-``lost`` — degrades its groups to
        direct-to-root: the server drains the orphaned queues itself
        and folds each group at its canonical position, so tree rounds
        stay deterministic through aggregator loss instead of stalling
        a barrier."""
        for t in self._l1:
            if t.flushed:
                continue
            fb = self._l1_fallback.get(t.group.idx)
            if fb is None:
                if t.is_alive():
                    continue
                self.faults.inc("agg_l1_fallbacks")
                self.log.warning(
                    f"aggregator {t.agg_id} died mid-round; draining "
                    f"group {t.group.idx} direct-to-root")
                fb = self._start_fallback(t.group, t.cluster,
                                          set(t.members))
            self._step_fallback(fb)
        for nid, glist in self._l1_remote.items():
            if nid in self._dead_nodes:
                for g in glist:
                    fb = self._l1_fallback.get(g.idx)
                    if fb is not None:
                        self._step_fallback(fb)
                continue
            if not self._node_dead(nid):
                continue
            self._dead_nodes.add(nid)
            self.faults.inc("agg_node_deaths")
            self._fleet_snapshot(nid, "agg_node",
                                 self._death_kind(nid, self._agg_nodes))
            self.log.warning(
                f"aggregator node {nid} is dead (process exit or "
                f"fleet-lost); draining its {len(glist)} group(s) "
                "direct-to-root")
            for g in glist:
                if g.parent is None and self._fold is not None \
                        and self._fold.has_key(g.stage, g.key):
                    continue   # its partial already landed at the root
                self.faults.inc("agg_l1_fallbacks")
                members = set(self._tree_narrowed.get(g.idx,
                                                      g.members))
                fb = self._start_fallback(g, self._cur_cluster,
                                          members)
                self._step_fallback(fb)

    def _start_fallback(self, group, cluster: int,
                        members: set) -> dict:
        fb = self._l1_fallback[group.idx] = {
            "group": group, "cluster": cluster,
            "members": set(members),
            "fold": agg_plane.StreamingFold(
                {group.stage: sorted(members)}, faults=self.faults),
            "asm": FrameAssembler(faults=self.faults),
            "seen": set(), "meta": [],
            # parentless groups book members/sums straight into the
            # root fold; groups under an interior parent publish a
            # substitute PartialAggregate into the parent's queue
            # instead (the parent's dedup absorbs the race where the
            # aggregator had actually flushed before being declared
            # dead) — booking BOTH ways would double-count members
            "book_direct": group.parent is None,
            "deadline": (time.monotonic()
                         + self.L1_FALLBACK_GRACE_S),
            "flushed": False}
        return fb

    def _children_draining(self, group) -> bool:
        """True while any CHILD group of an interior ``group`` has an
        unflushed fallback of its own: the child's drain will publish
        a substitute partial into THIS group's queue, so flushing (or
        abandoning) the parent now would strand members the child is
        actively recovering.  Bounded — every child fallback's own
        grace deadline abandons it eventually."""
        if group.level == 1:
            return False
        return any(f["group"].parent == group.idx and not f["flushed"]
                   for f in self._l1_fallback.values())

    def _step_fallback(self, fb: dict) -> None:
        if not fb["flushed"]:
            self._drain_fallback(fb)
        if not fb["flushed"] and time.monotonic() >= fb["deadline"]:
            if self._children_draining(fb["group"]):
                fb["deadline"] = (time.monotonic()
                                  + self.L1_FALLBACK_GRACE_S)
                return
            gone_keys = fb["members"] - fb["seen"]
            gone = self._member_clients(fb["group"], gone_keys)
            for _ in sorted(gone):
                self.faults.inc("agg_fallback_abandons")
            if gone_keys:
                self.log.warning(
                    f"fallback group {fb['group'].idx}: abandoning "
                    f"{sorted(gone_keys)} (dead aggregator consumed "
                    f"their frames; folding "
                    f"{len(fb['seen'])}/{len(fb['members'])} members)")
            self._agg_gone |= gone
            self._flush_fallback(fb)

    def _member_clients(self, group, keys) -> set:
        """The CLIENT ids behind a set of member keys — the ids
        themselves at level 1, the flattened (narrowed) client
        membership of the named child groups above it.  What the
        UPDATE barrier stops waiting for when a fallback abandons."""
        if group.level == 1:
            return set(keys)
        out: set = set()
        by_key = {g.key: g for g in self._tree_groups.values()}
        for key in keys:
            child = by_key.get(key)
            if child is not None:
                out |= self._member_clients(
                    child, self._tree_narrowed.get(child.idx,
                                                   child.members))
        return out

    def _drain_fallback(self, fb: dict) -> None:
        g = fb["group"]
        for m in agg_plane.drain_group_queue(
                self.bus, fb["cluster"], g.idx, self._cur_gen,
                fb["asm"], self.faults, log=self.log):
            if isinstance(m, Update):
                self._drain_fallback_update(fb, g, m)
            else:
                self._drain_fallback_partial(fb, g, m)
        if not fb["flushed"] and fb["seen"] >= fb["members"]:
            self._flush_fallback(fb)

    def _drain_fallback_update(self, fb: dict, g, u: Update) -> None:
        if g.level != 1 or u.client_id in fb["seen"]:
            self.faults.inc("agg_dup_drops")
            return
        fb["seen"].add(u.client_id)
        fb["deadline"] = time.monotonic() + self.L1_FALLBACK_GRACE_S
        self._fold_update(u)   # delta reconstruction, like the pump
        # drain_group_queue already gen-fenced this frame
        fb["fold"].add_update(copy.copy(u))  # slcheck: async-exempt
        fb["meta"].append({"client_id": u.client_id, "stage": u.stage,
                           "num_samples": u.num_samples, "ok": u.ok,
                           "telemetry": u.telemetry})
        u.params = None
        u.batch_stats = None
        if self.fleet is not None and u.telemetry:
            self.fleet.note_heartbeat(
                u.client_id, u.telemetry,
                via=self._digest_route.get(u.client_id))
        if fb["book_direct"]:
            self._updates.append(u)
        self.log.received(f"UPDATE {u.client_id} (fallback drain)")

    def _drain_fallback_partial(self, fb: dict, g,
                                m: PartialAggregate) -> None:
        """A dead INTERIOR group's queue holds its children's
        partials: recover them into the fallback sub-fold, keyed and
        dedup'd exactly as the dead aggregator would have."""
        key = agg_plane.group_key(m.group)
        if g.level == 1 or key in fb["seen"]:
            self.faults.inc("agg_dup_drops")
            return
        if m.codec or m.members_z:
            from split_learning_tpu.runtime.codec.partial import (
                PartialCodecError, decode_partial_msg,
            )
            try:
                decode_partial_msg(m, bases=self._partial_bases,
                                   base_gen=self._partial_base_gen)
            except PartialCodecError as e:
                self.faults.inc("partial_codec_errors")
                self.log.warning(f"fallback drain: undecodable "
                                 f"partial ({e}); dropped")
                return
        fb["seen"].add(key)
        fb["deadline"] = time.monotonic() + self.L1_FALLBACK_GRACE_S
        fb["fold"].add_partial(  # slcheck: async-exempt
            m.stage, key, m.sums, m.weight, m.dtypes,
            stat_sums=m.stat_sums, stat_weight=m.stat_weight,
            stat_dtypes=m.stat_dtypes, n_samples=m.n_samples)
        fb["meta"].extend(m.members or [])
        for mm in m.members or []:
            cid = mm.get("client_id")
            if cid is None:
                continue
            if self.fleet is not None and mm.get("telemetry"):
                self.fleet.note_heartbeat(
                    cid, mm["telemetry"],
                    via=self._digest_route.get(cid))
            if fb["book_direct"]:
                self._updates.append(Update(
                    client_id=cid, stage=int(mm.get("stage", m.stage)),
                    cluster=m.cluster, params=None, num_samples=0,
                    ok=bool(mm.get("ok", True)),
                    round_idx=m.round_idx))
        self.log.received(
            f"PARTIALAGGREGATE {m.aggregator_id} (fallback drain)")

    def _flush_fallback(self, fb: dict) -> None:
        """Close a fallback group: its sub-fold's partial sums land
        where the dead aggregator's would have — folded at the
        group's canonical position in the root fold when parentless,
        published as a substitute PartialAggregate into the parent's
        queue otherwise (same summation shape either way)."""
        g = fb["group"]
        stages, n = fb["fold"].partial()
        ent = stages.get(g.stage)
        if fb["book_direct"]:
            if ent:
                # members already gen-fenced at the drain
                self._fold.add_partial(  # slcheck: async-exempt
                    g.stage, g.key, ent["sums"], ent["weight"],
                    ent["dtypes"], stat_sums=ent["stat_sums"],
                    stat_weight=ent["stat_weight"],
                    stat_dtypes=ent["stat_dtypes"], n_samples=n)
            else:
                self._fold.drop(g.stage, g.key)
        else:
            ent = ent or {}
            msg = PartialAggregate(
                aggregator_id=f"aggregator_{fb['cluster']}_{g.idx}",
                cluster=fb["cluster"], group=g.idx, stage=g.stage,
                round_idx=self._cur_gen, sums=ent.get("sums"),
                weight=float(ent.get("weight") or 0.0),
                dtypes=ent.get("dtypes"),
                stat_sums=ent.get("stat_sums"),
                stat_weight=float(ent.get("stat_weight") or 0.0),
                stat_dtypes=ent.get("stat_dtypes"), n_samples=n,
                members=fb["meta"], level=g.level)
            q = agg_plane.aggregate_queue(fb["cluster"], g.parent)
            chunk = self.cfg.transport.chunk_mb << 20
            for part in encode_parts(msg, chunk):
                self.bus.publish(q, part)  # slcheck: wire=PartialAggregate
            self.log.sent(
                f"PARTIALAGGREGATE (fallback substitute for group "
                f"{g.idx} -> group {g.parent})")
        fb["flushed"] = True

    def _finish_l1(self) -> None:
        """Post-barrier aggregator-tree resolution, LEVEL-ASCENDING:
        live unflushed aggregators are told to flush (the server gave
        up on their stragglers) level by level, so an interior group
        still folds the partials the level below it just produced;
        remote nodes get one AggFlush each and cascade internally
        (runtime/aggnode.py); dead aggregators fall back to the
        direct-to-root drain; every fallback closes into the root
        fold.  Bounded — an aggregator that can neither flush nor die
        within the grace window is abandoned (its group key is
        dropped at finish)."""
        for lv in sorted({t.group.level for t in self._l1}):
            level_ts = [t for t in self._l1 if t.group.level == lv]
            for t in level_ts:
                if t.is_alive() and not t.flushed:
                    t.request_flush()

            def lv_done(ts=level_ts) -> bool:
                self._poll_l1()
                return all(
                    t.flushed or self._l1_fallback.get(
                        t.group.idx, {}).get("flushed")
                    for t in ts)
            deadline = time.monotonic() + 15.0
            while not lv_done() and time.monotonic() < deadline:
                self._pump_one(timeout=0.05)
        for nid in self._l1_remote:
            if nid not in self._dead_nodes:
                self.bus.publish(
                    reply_queue(nid),
                    encode(AggFlush(node_id=nid, gen=self._cur_gen)))
        if self._l1_remote:
            self.log.sent(f"AGGFLUSH -> {sorted(self._l1_remote)}")
        want = [(g.stage, g.key) for g in self._tree_roots] \
            or [(t.group.stage, t.group.key) for t in self._l1]

        def landed() -> bool:
            self._poll_l1()
            return all(self._fold.has_key(s, k) for s, k in want)

        if not landed():
            self._pump_until(
                landed, "aggregator flushes",
                deadline=time.monotonic() + 30.0)
        # forced close, LEVEL-ASCENDING: a child's flush publishes its
        # substitute into the parent's queue, so the parent (stepped
        # right after, flushed later in the same ordering) still folds
        # it instead of closing empty a microsecond earlier
        for fb in sorted(self._l1_fallback.values(),
                         key=lambda f: (f["group"].level,
                                        f["group"].idx)):
            if not fb["flushed"]:
                self._flush_fallback(fb)
            parent_idx = fb["group"].parent
            if parent_idx is not None:
                pfb = self._l1_fallback.get(parent_idx)
                if pfb is not None and not pfb["flushed"]:
                    self._drain_fallback(pfb)
        for t in self._l1:
            t.join(timeout=5.0)

    def _pump_until(self, pred: Callable[[], bool],
                    what: str | Callable[[], str],
                    deadline: float | None = None,
                    waiting: Callable[[], set] | None = None,
                    poll: Callable[[], None] | None = None,
                    sched_drop: bool = False) -> bool:
        """Drain rpc_queue until ``pred()``; False if the deadline passes.

        ``what`` may be a callable so the timeout warning names who is
        missing AT the deadline (an eager f-string would snapshot the
        missing set before any response arrived).

        ``waiting`` (when given) names the clients the barrier still
        needs: once EVERY one of them is FleetMonitor-``lost`` (no
        heartbeat for ``observability.liveness-timeout``), the wait
        gives up early — a dead client costs the round the liveness
        timeout, not the full barrier deadline.  A slow-but-alive
        straggler is never dropped by the monitor itself; with
        ``sched_drop`` (the NOTIFY/UPDATE barriers, when the
        scheduler is enabled) the scheduler's mid-round policy MAY
        stop waiting for a health-state-straggler past
        ``scheduler.barrier-grace-s`` — each such drop is journaled
        (``kind=sched``) and counted, and the caller's predicate
        consults ``_sched_gone`` so the barrier actually releases."""
        deadline = (time.monotonic() + self.client_timeout
                    if deadline is None else deadline)
        t_begin = time.monotonic()
        t_checked = 0.0
        t_stage = 0.0
        while not pred():
            if poll is not None:
                poll()   # e.g. L1 aggregator health -> fallback drain
                if pred():
                    return True
            now = time.monotonic()
            # stage-host death check (pipeline.remote, armed only
            # inside a round attempt): raises _StageHostLost so the
            # retry wrapper re-assigns and re-runs instead of this
            # barrier eating its deadline on a dead host's clients
            if (self._stage_watch
                    and now - t_stage >= self._WAIT_CHECK_S):
                t_stage = now
                if self.fleet is not None:
                    self.fleet.advance()
                self._check_stage_hosts()
            remain = deadline - now
            if remain <= 0:
                w = what() if callable(what) else what
                self.faults.inc("timeouts")
                self.log.warning(f"timeout waiting for {w}")
                return False
            # the liveness/scheduler checks walk the whole fleet
            # (advance + waiting-set rebuild are O(clients)); at 10k
            # clients running them per FRAME is an O(n^2) round wall,
            # so they are throttled to a coarse wall-clock cadence —
            # more than fine-grained enough for 45 s liveness
            # timeouts and multi-second scheduler graces
            if (waiting is not None and self.fleet is not None
                    and now - t_checked >= self._WAIT_CHECK_S):
                t_checked = now
                self._check_digest_nodes(now)
                lost = self.fleet.advance()
                missing = set(waiting())
                if missing and missing <= lost:
                    self.faults.inc("fleet_lost_drops", len(missing))
                    self.log.warning(
                        f"dropping lost client(s) {sorted(missing)}: "
                        f"no heartbeat within "
                        f"{self.fleet.liveness_timeout:g}s — barrier "
                        "released early")
                    return False
                if (sched_drop and missing
                        and self.scheduler is not None):
                    drop = self.scheduler.barrier_drop(
                        missing, self.fleet.states(),
                        waited_s=now - t_begin,
                        round_idx=getattr(self, "_cur_round",
                                          self._cur_gen))
                    if drop:
                        self._sched_release(drop)
                        continue   # re-check pred: barrier shrank
            if self._pump_one(timeout=min(remain, 0.25)):
                # drain what is already queued before re-evaluating
                # the barrier predicate: pred/waiting are O(clients),
                # and one evaluation per BATCH instead of per frame
                # is what keeps a 10k-client registration storm or
                # UPDATE wave linear in fleet size
                for _ in range(self._PUMP_BATCH - 1):
                    if not self._pump_one(timeout=0.0):
                        break
        return True

    #: wall-clock cadence of the O(clients) liveness/scheduler barrier
    #: checks inside _pump_until
    _WAIT_CHECK_S = 0.1
    #: frames drained per barrier-predicate evaluation
    _PUMP_BATCH = 256

    def _sched_release(self, drop: set) -> None:
        """Apply a scheduler mid-round drop: the barrier predicates
        stop counting these clients (``_sched_gone``) and the
        streaming fold's reorder window stops holding their slots —
        the same release discipline as a READY-barrier drop, so the
        fold order (and hence the aggregate) stays canonical over the
        clients that actually contributed."""
        self._sched_gone |= drop
        if self._fold is not None:
            for cid in sorted(drop):
                s = self._stage_of.get(cid)
                if s is not None and not self._fold.has_key(s, cid):
                    self._fold.drop(s, cid)

    # -- registration barrier ------------------------------------------------

    @property
    def registrations(self) -> list[Registration]:
        return list(self._registrations.values())

    def wait_for_registrations(self) -> list[Registration]:
        """Block until every configured client has registered
        (``src/Server.py:111-135``).

        Under ``topology.elastic-join`` the barrier counts PER STAGE:
        an elastic spare registering during startup must not mask a
        missing configured client (a raw total would release early),
        and extras beyond the configured counts are welcome — the
        initial plan simply includes them.
        """
        # full client_timeout here, NOT ready_timeout: registration covers
        # client process startup (jax import, transport connect) and a
        # miss is fatal rather than an elastic drop
        need = list(self.cfg.clients)

        def by_stage() -> list[int]:
            # out-of-range stages are deliberately kept registered in
            # non-elastic mode for fail-fast planning; they must not
            # crash (stage > len) or miscount (stage 0) the timeout
            # message that reports them
            counts = [0] * len(need)
            for r in self._registrations.values():
                if 1 <= r.stage <= len(need):
                    counts[r.stage - 1] += 1
            return counts

        if self.cfg.topology.elastic_join:
            enough = lambda: all(  # noqa: E731
                c >= n for c, n in zip(by_stage(), need))
            what = lambda: (  # noqa: E731
                f"per-stage registrations {by_stage()}/{need}")
        else:
            total = sum(need)
            enough = lambda: len(self._registrations) >= total  # noqa
            what = f"{total} registrations"
        self._pump_until(enough, what,
                         deadline=time.monotonic() + self.client_timeout)
        if not enough():
            raise RoundTimeout(
                f"registrations incomplete within {self.client_timeout}s:"
                f" per-stage {by_stage()} of {need}")
        self._planned_ids = set(self._registrations)
        return self.registrations

    _DEAD_AFTER = 2   # consecutive silent ROUNDS before pruning

    def refresh_plans(self, plans):
        """Elastic membership between rounds (topology.elastic-join).

        Extension beyond the reference (its client set is frozen at the
        registration barrier, ``src/Server.py:111-135``; a late client
        can never join and a dead one stalls every barrier forever):
        fold the finished round's alive/silent bookkeeping, drain
        between-round mail, then re-plan when the live set moved.
        Joiners (and everyone, when the re-plan moves the cuts) are
        marked so their next START carries shard weights even under a
        hold-weights strategy like FLEX.  When a full re-plan is
        impossible (e.g. a fixed distribution matrix pinned to the
        original membership), dead clients are still pruned surgically
        from the current plans so later rounds stop paying their
        barrier deadlines — only joining needs the planner.
        """
        if not self.cfg.topology.elastic_join:
            return None
        # fold the round: one miss per silent ROUND, not per invocation
        for cid in self._round_silent - self._round_alive:
            self._missed[cid] = self._missed.get(cid, 0) + 1
        for cid in self._round_alive:
            self._missed.pop(cid, None)
        self._round_alive = set()
        self._round_silent = set()
        while self._pump_one(timeout=0.0):
            pass
        dead = {c for c, n in self._missed.items()
                if n >= self._DEAD_AFTER}
        live = set(self._registrations) - dead
        if live == self._planned_ids:
            return None
        joined = sorted(live - self._planned_ids)
        pruned = sorted(self._planned_ids - live)
        regs = [r for c, r in self._registrations.items() if c in live]
        try:
            new_plans = plan_clusters(self.cfg, regs, exact_counts=False)
        except ValueError as e:
            if live != self._replan_failed_for:
                self.log.warning(f"elastic re-plan impossible: {e}")
                self._replan_failed_for = set(live)
            new_plans = self._prune_plans(plans, set(pruned))
            if new_plans is None:
                return None   # nothing safely removable; keep plans
            joined = []       # joining DOES need the planner
            live = self._planned_ids - set(pruned)
        else:
            self._replan_failed_for = None
            # a held shard survives only if the client keeps the SAME
            # layer range: compare per client (a re-plan can move a
            # client between clusters with different cuts even when no
            # single cluster's cuts changed) — joiners fall out of the
            # same comparison (no old range)
            old_rng = self._client_ranges(plans)
            new_rng = self._client_ranges(new_plans)
            self._needs_params |= {cid for cid, rng in new_rng.items()
                                   if old_rng.get(cid) != rng}
        for cid in pruned:
            self.bus.publish(reply_queue(cid), encode(Stop(
                reason="pruned: missed consecutive round barriers")))
            if self._delta_shadow is not None:
                # a pruned client's shadow is a full shard copy pinned
                # in server memory; under membership churn that leaks
                # without bound (a rejoiner full-frames anyway)
                self._delta_shadow.clear(cid)
            if self.fleet is not None:
                # stop scoring the pruned client (its zero rate would
                # drag the fleet median down for the survivors)
                self.fleet.forget(cid)
            self._digest_route.pop(cid, None)
        self.log.info(f"elastic re-plan: joined={joined} "
                      f"pruned={pruned}", "cyan")
        self._planned_ids = live
        return new_plans

    def _client_ranges(self, plans) -> dict:
        """client_id -> the (start, end) layer range it owns."""
        out = {}
        for p in plans:
            ranges = stage_ranges(len(self.specs), p.cuts)
            for s in range(1, p.n_stages + 1):
                for cid in p.clients[s - 1]:
                    out[cid] = ranges[s - 1]
        return out

    @staticmethod
    def _prune_plans(plans, pruned: set):
        """Remove ``pruned`` clients from existing plans without
        re-planning; None when any cluster would lose a whole stage
        (shared feasibility invariant: runtime/plan.py, also the
        scheduler's eviction path)."""
        from split_learning_tpu.runtime.plan import prune_plan_members
        return prune_plan_members(plans, pruned)

    def schedule_plans(self, plans, round_idx: int):
        """Closed-loop scheduler pass at a round boundary
        (``scheduler.enabled``; called by the round loop right after
        the elastic refresh).  Drains between-round mail so the fleet
        snapshot is current, runs the decision pass, then applies the
        transport side effects the scheduler itself must not own:
        STOP fan-out + shadow/telemetry reclaim for evictions (the
        same steps as the elastic prune), and ``_needs_params``
        marking for every client whose layer range a re-plan moved.
        Returns the replacement plans, or None when nothing changed."""
        if self.scheduler is None:
            return None
        fleet = {"clients": {}}
        if self.fleet is not None:
            while self._pump_one(timeout=0.0):
                pass
            self.fleet.advance()
            fleet = self.fleet.snapshot()
        profiles = {cid: (r.profile or {})
                    for cid, r in self._registrations.items()}
        out = self.scheduler.plan_round(plans, round_idx, fleet,
                                        profiles)
        if out.fan_in is not None and out.fan_in != self._agg.fan_in:
            # adopted fan-in retune: the next train_cluster plans its
            # tree at the new width (the journal already carries the
            # kind=sched "retune" record; this is just the application)
            import dataclasses as _dc
            self._agg = _dc.replace(self._agg, fan_in=int(out.fan_in))
            self.log.info(
                f"scheduler: aggregation fan-in retuned to "
                f"{out.fan_in}", "cyan")
        for cid in sorted(out.evict):
            # the elastic-drop path's teardown: STOP, drop the
            # registration (or the next elastic refresh would re-plan
            # the evicted client straight back in), reclaim the delta
            # shadow, stop fleet-scoring, forget the barrier ledger.
            # A recovered client rejoins by re-REGISTERing through
            # the elastic planner.
            self.bus.publish(reply_queue(cid), encode(Stop(
                reason="scheduler: evicted (persistent straggler)")))
            self._registrations.pop(cid, None)
            self._missed.pop(cid, None)
            if self._delta_shadow is not None:
                self._delta_shadow.clear(cid)
            if self.fleet is not None:
                self.fleet.forget(cid)
            self._digest_route.pop(cid, None)
            self._planned_ids.discard(cid)
        if self.fleet is not None:
            # scheduler attention pins the watchlist: a knob-carrying
            # (demoted/exempted) client keeps its exact server-side
            # view even when it climbs out of the digests' top-K —
            # the scheduler needs to SEE the recovery to revoke the
            # knobs.  Pins released on promotion next boundary.
            watched = self.scheduler.attention()
            for cid in watched - self._sched_watched:
                self.fleet.watch(cid)
            for cid in self._sched_watched - watched:
                self.fleet.watch(cid, pinned=False)
            self._sched_watched = watched
        if out.plans is None:
            return None
        old_rng = self._client_ranges(plans)
        new_rng = self._client_ranges(out.plans)
        # a re-plan that moved the cuts invalidates held shards: every
        # client whose layer range changed gets params on its next
        # START whatever the strategy's wire economy says
        self._needs_params |= {cid for cid, rng in new_rng.items()
                               if old_rng.get(cid) != rng}
        for plan in out.plans:
            self.log.info(
                f"Cluster {plan.cluster_id} (scheduler): "
                f"cuts={plan.cuts} "
                f"clients={[len(ids) for ids in plan.clients]}",
                "cyan")
        return out.plans

    # -- the remote round ----------------------------------------------------

    def train_cluster(self, plan: ClusterPlan, params, stats,
                      **kw) -> list[Update]:
        """One remote round for one cluster — see
        :meth:`_train_cluster_once` for the choreography.

        This wrapper adds the pipeline.remote death-retry loop: with
        stage-host slots assigned, a host death mid-attempt surfaces
        as :class:`_StageHostLost` from a barrier's pump; the wrapper
        re-assigns the dead host's slots to survivors (same client
        ids) and re-runs the attempt.  The re-run bumps the generation
        fence, so every straggler frame from the aborted attempt drops
        on arrival and the re-run fold is bit-identical to a
        fault-free round — surviving clients mid-round receive the
        fresh START, requeue-and-abort (``_redeliver_start``), and
        rejoin.  ``pipeline.retries`` caps attempts; exhaustion fails
        the round loudly."""
        if not self._stage_assignments:
            return self._train_cluster_once(plan, params, stats, **kw)
        retries = int(getattr(self.cfg.pipeline, "retries", 0))
        attempt = 0
        while True:
            self._stage_watch = True
            try:
                return self._train_cluster_once(plan, params, stats,
                                                **kw)
            except _StageHostLost as e:
                attempt += 1
                if attempt > retries:
                    raise RoundTimeout(
                        f"stage host {e.host_id} died and "
                        f"pipeline.retries={retries} re-assignment "
                        "attempt(s) are exhausted") from e
                self.log.warning(
                    f"round attempt aborted ({e}); re-assigning and "
                    f"re-running (attempt {attempt}/{retries})")
                self._recover_stage_host(e.host_id)
            finally:
                self._stage_watch = False

    def _train_cluster_once(self, plan: ClusterPlan, params, stats, *,
                      round_idx: int = 0, epochs: int = 1,
                      client_subset: list | None = None,
                      per_client_params: dict | None = None,
                      lr: float | None = None,
                      sync_all_later_stages: bool = False,
                      send_params: bool | dict = True,
                      send_weights: bool | dict = True) -> list[Update]:
        """One remote round for one cluster.

        FLEX wire economy (``other/FLEX/src/Server.py:140-143``):
        ``send_params`` False (bool, or {stage: bool}) sends START
        without weights (clients keep their local shard — client-side
        persistence between rounds); ``send_weights`` (same shape) rides
        the PAUSE so clients on non-aggregation rounds reply UPDATE
        without a state_dict (sample counts still flow; no weight bytes
        move).
        """
        stage1 = [c for c in plan.stage1_clients
                  if client_subset is None or c in client_subset]
        if not stage1:
            return []
        active = [(cid, 1) for cid in stage1]
        for s in range(2, plan.n_stages + 1):
            active += [(cid, s) for cid in plan.clients[s - 1]]

        ranges = stage_ranges(len(self.specs), plan.cuts)
        learning = dataclasses.asdict(self.cfg.learning)
        if lr is not None:
            learning["learning_rate"] = lr
        self._ready.clear()
        self._notified.clear()
        self._updates = []
        self._gen += 1
        self._cur_gen = self._gen
        self._cur_round = round_idx
        self._syn_live = False
        # async: the generation is the global model version — prune the
        # (client, version) dedup ledger past the admission window and
        # tell the fleet monitor where "now" is (version-lag scoring)
        self._folded_versions = {
            (c, v) for c, v in self._folded_versions
            if self._cur_gen - v
            <= self.cfg.learning.max_staleness + 1
            + (self.scheduler.max_staleness_bonus
               if self.scheduler is not None else 0)}
        if self._async and self.fleet is not None:
            # async only: in sync mode the generation is an invocation
            # counter, not a model version — feeding it to the monitor
            # would fabricate version lag for sequential clusters
            self.fleet.note_version(self._cur_gen)

        # streaming fold for this invocation: contributions fold in
        # canonical per-stage key order — sorted client ids, or L1
        # group keys when the aggregator tree (aggregation.fan-in) is
        # interposed.  Built BEFORE the START fan-out so the first
        # UPDATE to land already has somewhere to fold.
        groups = None
        self._group_of = {}
        self._l1 = []
        self._l1_fallback = {}
        self._l1_remote = {}
        self._dead_nodes = set()
        self._tree_groups = {}
        self._tree_roots = []
        self._agg_gone = set()
        self._sched_gone = set()
        self._stage_of = dict(active)
        self._agg_ingress_bytes = 0
        if self._streaming:
            fan_in = self._agg.fan_in
            expected: dict[int, list] = {}
            if fan_in and len(active) > fan_in:
                groups = agg_plane.plan_tree(active, fan_in,
                                             self._agg.levels)
                self._tree_groups = {g.idx: g for g in groups}
                self._tree_roots = agg_plane.root_groups(groups)
                self._group_of = {cid: g for g in groups
                                  if g.level == 1 for cid in g.members}
                for g in self._tree_roots:
                    expected.setdefault(g.stage, []).append(g.key)
            else:
                for cid, s in sorted(active):
                    expected.setdefault(s, []).append(cid)
            self._fold = agg_plane.StreamingFold(
                expected, backend=self._fold_backend,
                faults=self.faults, hists=self.hists)
            # partial-sum delta codec: pin this generation's per-stage
            # START base — the tree encodes (group mean - base) and
            # every receiver (interior node or this root) adds it back
            self._partial_bases = {}
            self._partial_base_gen = None
            if groups is not None and self._partial_codec is not None \
                    and self._partial_codec.kind == "delta":
                for s in range(1, plan.n_stages + 1):
                    a, b = ranges[s - 1]
                    self._partial_bases[s] = _np_tree(
                        shard_params(params, self.specs, a, b))
                self._partial_base_gen = self._cur_gen

        # 2LS fixed 1:1 edge<->head pairing: when in_clusters in-groups
        # each have their own head, the forward data plane runs over
        # pair-indexed queues instead of the shared cluster queue
        # (other/2LS/src/train/VGG16.py:23).  Requires a 2-stage plan
        # with exactly one head per in-cluster; otherwise the shared
        # queue's natural load balancing stays.
        pair_of: dict = {}
        n_in = self.cfg.topology.in_clusters
        if n_in > 1 and plan.n_stages == 2:
            from split_learning_tpu.runtime.context import client_groups
            groups = client_groups(len(stage1), min(n_in, len(stage1)))
            heads = plan.clients[1]
            if len(heads) == len(groups):
                for g, idxs in enumerate(groups):
                    for i in idxs:
                        pair_of[stage1[i]] = g
                    pair_of[heads[g]] = g
            else:
                self.log.warning(
                    f"in_clusters={n_in} but {len(heads)} heads for "
                    f"{len(groups)} in-groups: keeping shared queues")

        # window never wider than the feeders a head actually HEARS:
        # origins are trace[0] (the stage-1 feeders = DCSL "devices"),
        # and with 2LS pairing each head's queue receives only its own
        # group — a wider sda_size could never assemble a
        # distinct-origin window and every batch would crawl through
        # the idle-flush path
        if plan.n_stages >= 2:
            if pair_of:
                group_sizes = {}
                for cid in stage1:
                    g = pair_of.get(cid)
                    group_sizes[g] = group_sizes.get(g, 0) + 1
                n_feeders = min(group_sizes.values())
            else:
                n_feeders = len(stage1)
        else:
            n_feeders = 1
        sda = (min(self.cfg.aggregation.sda_size, n_feeders)
               if sync_all_later_stages else 1)

        # DCSL dispatch topology (other/DCSL/src/Scheduler.py:21-26,
        # :110-133): with SDA active, feeding clients scatter successive
        # batches round-robin across the next stage's PER-DEVICE queues
        # (per-device ``intermediate_queue_..._p{client_id}``) instead of
        # the shared cluster queue, and every later-stage device consumes
        # its own queue.
        # snapshot BEFORE the sda_route mutation below: strict-SDA
        # feeder sets must reflect the 2LS edge<->head pairing only —
        # the per-device routing entries are not a feeder partition
        pair_groups = dict(pair_of)
        sda_route = sda > 1 and plan.n_stages >= 2 and not pair_of
        if sda_route:
            for s in range(2, plan.n_stages + 1):
                for cid in plan.clients[s - 1]:
                    pair_of[cid] = cid

        # round-phase spans: sequential on the server thread, parented
        # under the round loop's "train" span, so the critical-path
        # walker can cross from the server timeline into client
        # timelines at the consume spans recorded inside each barrier
        fanout_span = self.tracer.start("start_fanout",
                                        round=round_idx,
                                        cluster=plan.cluster_id)
        fanout_t0 = time.time()
        shadow_refresh_s = 0.0
        # stage-ascending order (``active`` is built stage 1 first):
        # stage-1 clients' STARTs leave the socket before any later
        # stage's are even encoded, so the pipeline's feeders start
        # streaming while the rest of the fan-out is still encoding —
        # the fan-out half of the per-shard streaming discipline.
        # Per-stage shard trees are cached across clients: 10k stage-1
        # clients share one layer range, and re-slicing the same base
        # per client was a multi-ms/START tax at fleet scale (the
        # trees are read-only views of the same host arrays — exactly
        # the sharing the delta shadow already relies on).
        shard_cache: dict = {}
        for cid, s in active:
            a, b = ranges[s - 1]
            sp = (send_params.get(s, True)
                  if isinstance(send_params, dict) else bool(send_params))
            if cid in self._needs_params:
                # elastic joiner (no local shard yet) or a re-plan moved
                # the cuts: a weight-less START would crash the client's
                # shard reuse whatever the strategy's wire economy says
                sp = True
                self._needs_params.discard(cid)
            if sp:
                base = (per_client_params or {}).get(cid, params)
                key = (a, b) if base is params else None
                cached = shard_cache.get(key) \
                    if key is not None else None
                if cached is None:
                    shard_p = _np_tree(shard_params(base, self.specs,
                                                    a, b))
                    shard_s = _np_tree(shard_params(stats or {},
                                                    self.specs, a, b))
                    if key is not None:
                        shard_cache[key] = (shard_p, shard_s)
                else:
                    shard_p, shard_s = cached
            else:
                shard_p = shard_s = None
            # delta codec: keep a versioned shadow of EXACTLY what this
            # START carries, and advertise the version we hold — the
            # client sends a delta only against a matching base (a
            # weight-less START advertises the standing shadow).
            # Aggregator-tree members get NO advertisement: an L1
            # holds no shadow to reconstruct a delta against, so tree
            # rounds always full-frame (and the standing shadow is
            # reclaimed — it could never be used again)
            delta_ver = None
            group = self._group_of.get(cid)
            if self._delta_shadow is not None:
                if group is not None:
                    self._delta_shadow.clear(cid)
                elif sp:
                    # the shadow stores VIEWS of the same host arrays
                    # the sharded update fetched (one device->host
                    # fetch per stage, _np_tree/shard_params slice
                    # without copying) — no fp32 re-materialization
                    t_sh = time.perf_counter()
                    self._delta_shadow.note_sent(cid, self._cur_gen,
                                                 shard_p)
                    shadow_refresh_s += time.perf_counter() - t_sh
                    delta_ver = self._cur_gen
                else:
                    delta_ver = self._delta_shadow.version_for(cid)
            label_counts = None
            if s == 1:
                label_counts = np.asarray(
                    plan.label_counts[plan.stage1_clients.index(cid)])
            end_layer = -1 if s == plan.n_stages else b
            # per-shard START streaming: a big shard frame splits into
            # crc'd SLTC chunks published as they are cut, so the
            # client's FrameAssembler starts receiving shard bytes
            # while the tail of the frame is still encoding (and
            # later-stage STARTs haven't been touched yet)
            start_parts = encode_parts(Start(
                start_layer=a, end_layer=end_layer,
                cluster=plan.cluster_id, params=shard_p,
                batch_stats=shard_s, learning=learning,
                label_counts=label_counts, round_idx=round_idx,
                extra={"epochs": epochs, "sda_size": sda,
                       # strict barriers work at ANY depth: stage-1
                       # feeders fence their epochs (EpochEnd) and
                       # middle stages propagate the marker downstream
                       # after the activations it fences, so the head's
                       # dead-barrier rule sees root-origin fences even
                       # through a deep pipeline
                       "sda_strict": self.cfg.aggregation.sda_strict,
                       # copies of each (origin, epoch) fence this
                       # client must collect before acting on it (head:
                       # record; middle: relay downstream): every
                       # previous-stage device sends/relays one copy,
                       # and only the LAST copy's per-queue FIFO
                       # position proves all activations it fences have
                       # arrived — a single early copy can overtake
                       # batches routed via a slower previous-stage
                       # device.  Stage 2 hears each feeder directly
                       # (one copy).
                       "sda_fence_quorum": (
                           1 if s <= 2
                           else max(1, len(plan.clients[s - 2]))),
                       # the strict head must know its FULL feeder set:
                       # draining leftovers is only safe once every
                       # feeder that could still extend a window has
                       # fenced its epoch — "everyone currently
                       # buffered is done" is not enough (a quiet
                       # feeder may still be mid-batch).  CONSUMERS
                       # only (stages >= 2): feeders are producers,
                       # never drain against the set — and shipping a
                       # 10k-id list inside every stage-1 START was
                       # the O(n^2) half of a fleet-scale fan-out
                       "sda_feeders": (
                           None if s == 1 else
                           ([c for c in stage1
                             if pair_groups.get(c)
                             == pair_groups.get(cid)]
                            if pair_groups else list(stage1))),
                       "n_stages": plan.n_stages,
                       "pair": pair_of.get(cid),
                       "sda_peers": (list(plan.clients[s])
                                     if sda_route and s < plan.n_stages
                                     else None),
                       "refresh": self.cfg.distribution.refresh,
                       # clients adopt the server's run-scoped trace id
                       # so all participants' spans merge onto ONE
                       # trace, across processes
                       "trace_id": self.tracer.trace_id,
                       "delta_base_version": delta_ver,
                       # aggregator tree: publish the round UPDATE to
                       # this group's aggregate queue instead of rpc
                       "agg_group": (group.idx if group is not None
                                     else None),
                       # scheduler-granted per-client knob retunes
                       # (runtime/scheduler.py): e.g. a heavier
                       # activation codec for a wire-slow straggler.
                       # None for undemoted clients and with the
                       # scheduler off — the client's config applies.
                       "sched": (self.scheduler.knobs_for(cid)
                                 if self.scheduler is not None
                                 else None),
                       # hierarchical heartbeat roll-up: the digest
                       # queue this client's beats publish to (its
                       # aggregator node folds them into FleetDigest
                       # frames), None = direct rpc heartbeats
                       "digest": self._digest_route_for(cid),
                       "gen": self._cur_gen}),
                self.cfg.transport.chunk_mb << 20)
            for part in start_parts:
                self.bus.publish(reply_queue(cid), part)  # slcheck: wire=Start
            self.log.sent(f"START -> {cid} layers=[{a}, {end_layer}]"
                          + ("" if sp else " (no weights)"))
        fanout_span.end()
        # round-boundary fan-out wall: with the previous invocation's
        # kind=agg update window this bounds the serial weight-update
        # bubble (finish + re-shard + encode + publish) the clients'
        # sync-overlap ticks hide
        self.log.metric(kind="update", gen=self._cur_gen,
                        round_idx=round_idx, cluster=plan.cluster_id,
                        fanout_s=round(time.time() - fanout_t0, 6),
                        fanout_t0=round(fanout_t0, 6),
                        fanout_t1=round(time.time(), 6),
                        shadow_refresh_s=round(shadow_refresh_s, 6),
                        n_starts=len(active))
        # also surfaced on this invocation's kind=agg record below —
        # note the boundary: this is the cost of the fan-out that
        # OPENED this invocation (delivering the previous fold's
        # params), so the agg record shows the adjacent boundary's
        # shadow-write cost; kind=update above is the exact per-round
        # attribution
        self._fanout_shadow_s = shadow_refresh_s
        if self._delta_shadow is not None:
            # shadow memory audit: bytes pinned by per-client base
            # copies, refreshed whenever the set can have changed
            self.gauges.set("agg_shadow_bytes",
                            self._delta_shadow.nbytes())

        ids = {cid for cid, _ in active}
        with self.tracer.span("ready_wait", round=round_idx):
            ready_ok = self._pump_until(
                lambda: ids <= self._ready,
                lambda: f"READY from {ids - self._ready}",
                deadline=time.monotonic() + self.ready_timeout,
                waiting=lambda: ids - self._ready)
        if not ready_ok:
            ids &= self._ready  # drop unresponsive clients mid-round
        if self._fold is not None and groups is None:
            # flat streaming: stop the reorder window waiting for
            # clients dropped at the READY barrier
            for cid, s in active:
                if cid not in ids:
                    self._fold.drop(s, cid)
        if groups is not None:
            # aggregator tree: dispatch the tree's interior nodes now,
            # with LEVEL-1 membership narrowed to the responsive set
            # (a client dropped at READY will never publish; its
            # aggregator must not hold the group's flush for it).
            # Interior groups keep every child key — child workers
            # always publish, an empty group immediately.
            narrowed = {
                g.idx: ([m for m in g.members if m in ids]
                        if g.level == 1 else list(g.members))
                for g in groups}
            self._tree_narrowed = narrowed
            self._cur_cluster = plan.cluster_id
            node_ids = [n for n in sorted(self._agg_nodes)
                        if not self._node_dead(n)]
            if self._agg.remote and not node_ids:
                self.log.warning(
                    "aggregation.remote: no live aggregator nodes "
                    "adopted — falling back to thread-mode L1s")
            if self._agg.remote and node_ids:
                self._dispatch_remote(plan, groups, narrowed, node_ids,
                                      round_idx)
            else:
                self._spawn_l1_threads(plan, groups, narrowed)
            self._agg_topology = {
                "fan_in": self._agg.fan_in,
                "levels": self._agg.levels,
                "remote": bool(self._l1_remote),
                "gen": self._cur_gen,
                "groups": [{
                    "idx": g.idx, "stage": g.stage, "level": g.level,
                    "parent": g.parent,
                    "members": len(narrowed[g.idx]),
                    "node": next((n for n, gl in
                                  self._l1_remote.items()
                                  if any(x.idx == g.idx for x in gl)),
                                 None)}
                    for g in groups],
            }
            self.log.info(
                f"aggregator tree: {len(groups)} group(s), fan-in "
                f"{self._agg.fan_in}, levels {self._agg.levels}"
                + (f", remote across {len(self._l1_remote)} node(s)"
                   if self._l1_remote else " (threads)"), "cyan")
        stage_of = dict(active)
        syn_span = self.tracer.start("syn_fanout", round=round_idx)
        # strict-SDA liveness under client loss (ADVICE r5): the
        # fence quorum / feeder set sent in START counted the
        # STATIC plan, but a previous-stage client dropped at the
        # READY barrier will never send its fence copies — the
        # static quorum could never be met and the strict drain
        # would stall to round timeout.  Recompute both from the
        # RESPONSIVE set and rebroadcast them with SYN.  Computed for
        # EVERY active client (not just the responsive set): a late
        # READY joiner's pump-sent SYN reuses its entry.
        self._syn_overrides = {}
        # stage-1 clients never consume a feeder set (they produce);
        # building a per-client O(stage1) list for each of them was
        # the other O(n^2) term of a fleet-scale round open — they
        # get (quorum=1, no override) in O(1)
        responsive_s1 = [c for c in stage1 if c in ids]
        for cid, s in active:
            if s == 1:
                self._syn_overrides[cid] = (1, None)
                continue
            quorum = (1 if s <= 2 else max(1, sum(
                1 for c in plan.clients[s - 2] if c in ids)))
            feeders = [c for c in responsive_s1
                       if not pair_groups
                       or pair_groups.get(c) == pair_groups.get(cid)]
            self._syn_overrides[cid] = (quorum, feeders)
        for cid in ids:
            quorum, feeders = self._syn_overrides[cid]
            self.bus.publish(reply_queue(cid), encode(Syn(
                round_idx, sda_fence_quorum=quorum,
                sda_feeders=feeders)))
        self.log.sent(f"SYN -> {sorted(ids)}")
        syn_span.end()
        # async: keep the SYN window open — a straggler's late READY
        # (it was still uploading its previous round) gets its SYN from
        # the pump and joins this round late instead of idling it out
        self._syn_live = self._async
        self._syn_round = round_idx

        s1_ids = set(stage1) & ids
        quorum_n = self.cfg.learning.async_quorum
        # scheduler demotions lower a compute-slow straggler's quorum
        # share: exempt clients don't count toward quorum denominators
        # (their contribution folds late through the widened staleness
        # window instead of holding the round)
        exempt = ({c for c in ids if self.scheduler.quorum_exempt(c)}
                  if self.scheduler is not None else set())
        deadline = time.monotonic() + self.client_timeout
        with self.tracer.span("notify_wait", round=round_idx):
            if self._async and quorum_n:
                # async quorum: the round moves on once enough feeders
                # exhausted their data — a high-RTT feeder finishes its
                # contribution late (stale-admitted next cut) instead
                # of stalling the fleet
                # exempt clients shrink the denominator, floored at 1
                # so a FULLY-exempt stage still owes one NOTIFY — but
                # a genuinely EMPTY stage keeps the old instant-pass
                # (need 0): flooring that case would hang the barrier
                # for the full client_timeout on a set that can never
                # respond
                s1_need = min(max(1, len(s1_ids - exempt))
                              if s1_ids else 0,
                              max(1, quorum_n))
                self._pump_until(
                    lambda: len(self._notified & s1_ids) >= s1_need,
                    f"NOTIFY quorum {s1_need}/{len(s1_ids)}",
                    deadline=deadline,
                    waiting=lambda: s1_ids - self._notified)
            else:
                self._pump_until(
                    lambda: s1_ids - self._sched_gone
                    <= self._notified,
                    "NOTIFY from stage-1 clients",
                    deadline=deadline,
                    waiting=lambda: (s1_ids - self._notified
                                     - self._sched_gone),
                    sched_drop=True)
        pause_span = self.tracer.start("pause_fanout", round=round_idx)
        # late-READY joiners (async) get their PAUSE too — they are
        # training and must upload like everyone else
        pause_ids = set(ids) | (self._ready & {c for c, _ in active})
        for cid in pause_ids:
            if isinstance(send_weights, dict):
                flag = bool(send_weights.get(stage_of[cid], True))
            else:
                flag = bool(send_weights)
            self.bus.publish(reply_queue(cid),
                             encode(Pause(send_weights=flag)))
        self.log.sent(f"PAUSE -> {sorted(pause_ids)}")
        pause_span.end()

        # _agg_gone: members a dead L1 consumed-then-lost — their
        # UPDATE can never arrive, so the barrier stops counting them.
        # fresh_ids folds INCREMENTALLY: re-scanning the whole updates
        # list per predicate evaluation is an O(n^2) barrier over a
        # 10k-client UPDATE wave.
        fresh_seen: set = set()
        fresh_idx = [0]

        def fresh_ids() -> set:
            ups = self._updates
            for u in ups[fresh_idx[0]:]:
                if (u.version if u.version is not None
                        else u.round_idx) == self._cur_gen:
                    fresh_seen.add(u.client_id)
            fresh_idx[0] = len(ups)
            return fresh_seen
        if self._async and quorum_n:
            # bounded-staleness version cut: a new global version cuts
            # once async-quorum FRESH contributions folded; stragglers
            # contribute late through the admission window instead of
            # holding the barrier.  Scheduler-exempt clients shrink
            # the denominator — a demoted compute-slow client's share
            # of the quorum is zero.
            # same floor discipline as the NOTIFY quorum: fully-exempt
            # still owes one fresh fold, genuinely-empty passes
            need = min(max(1, quorum_n),
                       max(1, len(ids - exempt)) if ids else 0)
            got = lambda: len((fresh_ids() & ids)  # noqa: E731
                              | ((self._agg_gone | self._sched_gone)
                                 & ids)) >= need
            missing = lambda: (ids - fresh_ids()  # noqa: E731
                               - self._agg_gone - self._sched_gone)
            what = lambda: (f"UPDATE quorum {need}/{len(ids)} "  # noqa
                            f"(missing {sorted(missing())})")
        else:
            # fresh_ids, NOT the raw barrier list: in async mode a
            # straggler's stale-admitted PREVIOUS-version Update also
            # rides self._updates, and counting it would cut the round
            # without the client's fresh contribution (in sync the two
            # sets are identical — only current-gen Updates fold)
            got = lambda: (fresh_ids() | self._agg_gone  # noqa: E731
                           | self._sched_gone) >= ids
            missing = lambda: (ids - fresh_ids()  # noqa: E731
                               - self._agg_gone - self._sched_gone)
            what = lambda: "UPDATE from " + str(missing())  # noqa
        with self.tracer.span("update_wait", round=round_idx):
            self._pump_until(
                got, what,
                deadline=time.monotonic() + self.client_timeout,
                waiting=missing,
                poll=(self._poll_l1 if self._l1 or self._l1_remote
                      else None),
                sched_drop=True)
        self._syn_live = False
        if self._l1 or self._l1_remote:
            self._finish_l1()
        updates = list(self._updates)
        self._updates = []
        if self._fold is not None:
            # the overlapped fold already consumed (and freed) every
            # tree; what is left is the O(1) divide + optimizer step.
            # The aggregate span carries the overlapped fold wall so
            # sl_trace/sl_perf attribute the phase honestly.
            fold, self._fold = self._fold, None
            m = float(self._agg.server_momentum)
            self._update_t0 = time.time()
            with self.tracer.span(
                    "aggregate", round=round_idx,
                    cluster=plan.cluster_id,
                    overlapped_fold_s=round(fold.fold_s, 6)):
                # fused sharded update (aggregation.update-sharded):
                # each stage's divide+momentum+cast runs as one
                # donated program, all stages dispatched before any
                # fetch — stage k's single device->host fetch overlaps
                # stage k+1's device compute.  The on_stage hook marks
                # each stage's completion on the aggregate span so
                # sl_trace shows the per-shard pipeline.
                result = fold.finish(
                    base=params if m else None, momentum=m,
                    velocity=(self._agg_velocity.setdefault(
                        plan.cluster_id, {}) if m else None),
                    fused=self._agg.update_sharded,
                    on_stage=lambda s, p, st: self.tracer.record(
                        "update_stage", time.time(), time.time(),
                        round=round_idx, stage=s))
            self._update_t1 = time.time()
            updates = agg_plane.UpdateBatch(updates)
            updates.fold = result
            self.log.metric(
                kind="agg", gen=self._cur_gen, round_idx=round_idx,
                cluster=plan.cluster_id,
                backend=(self._fold_backend.name
                         if self._fold_backend is not None else "host"),
                fan_in=(self._agg.fan_in if groups is not None else 0),
                levels=(self._agg.levels if groups is not None else 0),
                remote_nodes=len(self._l1_remote),
                node_deaths=len(self._dead_nodes),
                # rpc-wire bytes of the PartialAggregate frames that
                # landed at this root (chunked streams fully counted)
                # — the ingress the partial codec exists to shrink
                root_ingress_bytes=self._agg_ingress_bytes,
                partial_codec=(None if self._partial_codec is None
                               else self._partial_codec.kind),
                fold_s=result.fold_s, folded=result.folded,
                partials=result.partials,
                window_hwm=result.window_hwm,
                peak_tree_copies=result.peak_tree_copies,
                n_samples=result.n_samples,
                # round-boundary update wall (divide + FedAvgM + cast
                # + per-stage fetch) — the serial bubble the sharded
                # update shrinks and the clients' sync-overlap hides.
                # Wall-clock t0/t1 let the bench intersect this window
                # with client overlap activity on the same host clock.
                update_sharded=bool(self._agg.update_sharded),
                update_s=result.update_s,
                update_t0=round(self._update_t0, 6),
                update_t1=round(self._update_t1, 6),
                stage_update_ms=result.stage_update_ms,
                shadow_refresh_s=round(
                    getattr(self, "_fanout_shadow_s", 0.0), 6))
            self.log.info(
                f"streamed aggregate: folded={result.folded} "
                f"(partials={result.partials}) fold={result.fold_s:.3f}s"
                f" peak_tree_copies={result.peak_tree_copies:g}",
                "cyan")
            self._l1 = []
            self._l1_fallback = {}
            self._l1_remote = {}
        # elastic liveness bookkeeping, folded per ROUND at the next
        # refresh_plans: any UPDATE during the round marks a client
        # alive even if it sat out other invocations of a sequential
        # strategy (topology.elastic-join)
        responded = {u.client_id for u in updates}
        self._round_alive |= responded
        self._round_silent |= {cid for cid, _ in active} - responded
        # wire audit: CUMULATIVE transport-wide publish bytes by queue
        # kind (reply_* = server control/weights down; rpc = client
        # control/weights up; data = activation/gradient plane).  On the
        # shared in-process bus this covers every participant; over TCP
        # each process's transport counts its own publishes.  Consumers
        # should diff successive records — values never reset.
        totals = {"reply": 0, "rpc": 0, "data": 0}
        for q, n in self.bus.bytes_out_snapshot().items():
            kind = ("reply" if q.startswith("reply_")
                    else "rpc" if q == RPC_QUEUE else "data")
            totals[kind] += n
        # per-process wire counters ride the same record (bytes in/out
        # by plane, encode/decode seconds, async sender high-water
        # mark) and the end-of-round log line, so the wire's cost is
        # auditable next to its volume
        wsnap = {k: v for k, v in self.wire.snapshot().items() if v}
        self.log.metric(kind="wire", gen=self._cur_gen,
                        round_idx=round_idx, cluster=plan.cluster_id,
                        cumulative_reply_bytes=totals["reply"],
                        cumulative_rpc_bytes=totals["rpc"],
                        cumulative_data_bytes=totals["data"],
                        **wsnap)
        if wsnap:
            self.log.info(
                "round wire (cumulative): "
                f"out={wsnap.get('bytes_out_total', 0)}B "
                f"in={wsnap.get('bytes_in_total', 0)}B "
                f"encode={wsnap.get('encode_s', 0):.3f}s "
                f"decode={wsnap.get('decode_s', 0):.3f}s "
                f"sendq_hwm={wsnap.get('send_queue_hwm', 0)}")
        # failure/recovery observability: CUMULATIVE fault counters
        # (drops, timeouts, redeliveries, dedup_hits, reconnects, ...)
        # from this process's transport stack — chaos runs must be
        # auditable, not silently self-healing.  Same diff-successive-
        # records contract as the wire bytes above.  Logged only when
        # something actually happened, so clean runs stay clean.
        snap = {k: v for k, v in self.faults.snapshot().items() if v}
        if snap:
            if snap != self._fault_base:
                self.log.info(
                    "round faults (cumulative): "
                    + " ".join(f"{k}={v}"
                               for k, v in sorted(snap.items())),
                    "yellow")
                self._fault_base = snap
            self.log.metric(kind="faults", gen=self._cur_gen,
                            round_idx=round_idx,
                            cluster=plan.cluster_id, **snap)
        # latency percentiles: this process's histograms (frame RTT,
        # step, encode/decode) merged with the process-wide transport
        # clocks (broker queue-wait, reliable-envelope RTT), which have
        # no per-participant registry in reach.  Cumulative — diff
        # successive records like every counter above.
        from split_learning_tpu.runtime.trace import default_histograms
        hsnap = {**default_histograms.snapshot(),
                 **self.hists.snapshot()}
        if hsnap and hsnap != getattr(self, "_hist_base", None):
            self._hist_base = hsnap
            self.log.metric(kind="latency", gen=self._cur_gen,
                            round_idx=round_idx,
                            cluster=plan.cluster_id, **hsnap)
        # fleet health at round end: one kind=fleet metrics record (the
        # per-client states, rates, straggler scores AND the latest
        # counter snapshots each heartbeat flushed — so a client that
        # crashed mid-round still has its counters on disk) plus a
        # one-line summary.  Same per-invocation cadence as the wire/
        # fault records above.
        if self.fleet is not None:
            # drain queued-but-unpumped heartbeats first so the record
            # reflects what clients SENT, not when we last listened
            while self._pump_one(timeout=0.0):
                pass
            self.fleet.advance()
            fsnap = self.fleet.snapshot()
            if self.scheduler is not None:
                # mirror the /fleet scheduler view into the journaled
                # record so sl_top --journal renders the same
                # CLUSTER/SCHED columns as the live endpoint
                self.scheduler.annotate_fleet(fsnap)
            self.log.metric(kind="fleet", gen=self._cur_gen,
                            round_idx=round_idx,
                            cluster=plan.cluster_id, fleet=fsnap)
            counts = fsnap["counts"]
            unhealthy = {c: v["state"]
                         for c, v in fsnap["clients"].items()
                         if v["state"] != "healthy"}
            line = ("fleet: " + " ".join(
                f"{s}={n}" for s, n in counts.items() if n))
            if unhealthy:
                line += " (" + " ".join(
                    f"{c}:{s}" for c, s in sorted(unhealthy.items())) \
                    + ")"
            self.log.info(line, "yellow" if unhealthy else "cyan")
        # a finished invocation's spans must be durable before the next
        # one (or a crash) — the journal buffers between flushes
        self.tracer.flush()
        return updates

    def stop_all(self, reason: str = "training complete"):
        for reg in self.registrations:
            self.bus.publish(reply_queue(reg.client_id),
                             encode(Stop(reason=reason)))
        for nid in self._agg_nodes:
            self.bus.publish(reply_queue(nid),
                             encode(Stop(reason=reason)))
        for hid in self._stage_hosts:
            self.bus.publish(reply_queue(hid),
                             encode(Stop(reason=reason)))
        # the STOP fan-out must actually leave this process before the
        # caller tears the broker down
        flush = getattr(self.bus, "flush", None)
        if flush is not None:
            flush(timeout=10.0)
        self.log.sent(f"STOP -> all ({reason})")
        for l1_log in self._l1_logs.values():
            l1_log.close()
        self._l1_logs = {}
        self.tracer.close()


def _np_tree(tree: Any) -> Any:
    import jax
    return jax.tree_util.tree_map(np.asarray, tree)


class ProtocolServer:
    """Top-level server process (reference ``server.py:20-30``)."""

    def __init__(self, cfg: Config, transport: Transport | None = None,
                 logger: Logger | None = None,
                 client_timeout: float = 600.0,
                 ready_timeout: float | None = None):
        self.cfg = cfg
        self.log = logger or Logger.for_run(cfg, "server",
                                            console=True)
        if transport is None:
            from split_learning_tpu.runtime.chaos import (
                make_runtime_transport,
            )
            transport = make_runtime_transport(cfg, "server")
        bus = transport
        bus.purge()   # queue hygiene at startup (src/Utils.py:8-32)
        self.ctx = ProtocolContext(cfg, bus, logger=self.log,
                                   client_timeout=client_timeout,
                                   ready_timeout=ready_timeout)
        # aggregation.nodes: spawn the aggregator subprocesses this
        # deployment wants (tcp only — validated at config load); the
        # nodes connect to the broker, AggHello into the rpc pump, and
        # are adopted before planning (serve() waits for them)
        self._spawned_nodes: list = []
        if cfg.aggregation.remote and cfg.aggregation.nodes:
            import pathlib

            from split_learning_tpu.runtime.aggnode import (
                spawn_node, write_node_config,
            )
            cfg_path = pathlib.Path(
                getattr(self.log, "output_dir", None)
                or cfg.log_path) / "aggregator_config.json"
            write_node_config(cfg, cfg_path)
            for i in range(cfg.aggregation.nodes):
                nid = f"aggregator_node_{i}"
                proc = spawn_node(cfg_path, nid)
                self.ctx._agg_nodes.setdefault(nid, {})["proc"] = proc
                self._spawned_nodes.append(proc)
            self.log.info(
                f"spawned {cfg.aggregation.nodes} aggregator "
                "node(s)", "cyan")
        # pipeline.hosts: spawn the stage-host subprocesses this
        # deployment wants (tcp only — validated at config load); the
        # hosts connect to the broker, StageHello into the rpc pump,
        # and are adopted + assigned before the registration barrier
        # (their inner clients ARE the later-stage registrations)
        self._spawned_hosts: list = []
        if cfg.pipeline.remote and cfg.pipeline.hosts:
            import pathlib

            from split_learning_tpu.runtime.stagehost import (
                spawn_stage_host, write_host_config,
            )
            cfg_path = pathlib.Path(
                getattr(self.log, "output_dir", None)
                or cfg.log_path) / "stagehost_config.json"
            write_host_config(cfg, cfg_path)
            ncpu = os.cpu_count() or 1
            for i in range(cfg.pipeline.hosts):
                hid = f"stage_host_{i}"
                # pin_cpus: one core per host, core 0 left to the
                # server + feeders — placement-stable measurement
                cpu = ((i + 1) % ncpu
                       if cfg.pipeline.pin_cpus and ncpu > 1 else None)
                proc = spawn_stage_host(cfg_path, hid, cpu=cpu)
                self.ctx._stage_hosts.setdefault(hid, {})["proc"] = proc
                self._spawned_hosts.append(proc)
            self.log.info(
                f"spawned {cfg.pipeline.hosts} stage host(s)", "cyan")
        # real-time export (observability.http-port): /metrics serves
        # Prometheus text, /fleet the JSON health snapshot — what
        # tools/sl_top.py polls for the live terminal view.  Render
        # callbacks advance the monitor first so a mid-wait scrape
        # sees current health states, not the last pump's.
        self.exporter = None
        obs = getattr(cfg, "observability", None)
        # on-demand profiler hook (runtime/perf.py): POST /profile
        # arms a jax.profiler window the round loop opens at the next
        # round boundary; artifact lands under the run-scoped
        # profile/ directory.  Attached to the context so run_training
        # drives the window whatever backend is underneath.
        from split_learning_tpu.runtime.perf import (
            ProfileCapture, profile_output_dir, register_process_capture,
        )
        self.ctx.perf_capture = ProfileCapture(
            profile_output_dir(cfg, self.log), log=self.log)
        # in-process cells (client threads sharing this process) tick
        # this capture from their hot loops, closing a steps=K window
        # after K steps; separate client processes can't — there the
        # round boundary closes it (see register_process_capture)
        register_process_capture(self.ctx.perf_capture)
        # broker-plane self-telemetry (broker.shards): each shard's
        # event loop serves a stats frame on its control queue; the
        # server sweeps the plane at most every broker.stats-interval
        # seconds, mirrors plane-wide sums into the broker_* gauges
        # (so /metrics carries them) and hands the per-shard rows to
        # /fleet, where sl_top renders them as ROLE=broker rows
        self._broker_stats_cache: dict = {"t": 0.0, "stats": None,
                                          "busy": False}

        def _refresh_broker_stats() -> None:
            from split_learning_tpu.runtime.bus import (
                collect_broker_stats,
            )
            cache = self._broker_stats_cache
            try:
                stats = collect_broker_stats(
                    cfg.transport.host, cfg.transport.port,
                    cfg.broker.shards)
                cache["stats"], cache["t"] = stats, time.monotonic()
                live = [s for s in stats if "error" not in s]
                g = self.ctx.gauges
                g.set("broker_shards_up", len(live))
                for gauge, key in (
                        ("broker_conns", "conns"),
                        ("broker_queues", "queues"),
                        ("broker_depth", "depth"),
                        ("broker_depth_hwm", "depth_hwm"),
                        ("broker_parked_gets", "parked_gets"),
                        ("broker_bytes_in", "bytes_in"),
                        ("broker_bytes_out", "bytes_out")):
                    g.set(gauge, sum(s.get(key, 0) for s in live))
            finally:
                cache["busy"] = False

        def _broker_stats() -> list | None:
            """Cached shard-stats rows; a stale cache triggers an
            ASYNC refresh and serves the previous sweep — dialing the
            shards inline would add their connect latency to every
            /fleet scrape (observed as scraper-side timeouts while a
            compile starves the exporter threads)."""
            if (cfg.transport.kind != "tcp"
                    or cfg.broker.stats_interval <= 0):
                return None
            cache = self._broker_stats_cache
            now = time.monotonic()
            if (now - cache["t"] >= cfg.broker.stats_interval
                    and not cache["busy"]):
                cache["busy"] = True
                threading.Thread(target=_refresh_broker_stats,
                                 daemon=True,
                                 name="broker-stats").start()
            return cache["stats"]

        self._broker_stats = _broker_stats
        if obs is not None and obs.http_port is not None:
            from split_learning_tpu.runtime.telemetry import (
                TelemetryExporter, render_prometheus,
            )
            ctx = self.ctx

            def _metrics() -> str:
                if ctx.fleet is not None:
                    ctx.fleet.advance()
                _broker_stats()   # refresh the broker_* gauges
                return render_prometheus(
                    fleet=ctx.fleet, faults=ctx.faults, wire=ctx.wire,
                    hists=ctx.hists, gauges=ctx.gauges,
                    max_client_series=obs.max_client_series)

            def _fleet(query: dict | None = None) -> dict:
                query = query or {}
                if ctx.fleet is None:
                    snap = {"clients": {}, "counts": {},
                            "transitions": []}
                else:
                    ctx.fleet.advance()
                    # default shape: full detail (series included)
                    # while the tracked population is small; summary
                    # (no ring-buffer series) past the series cap.
                    # ?full=1 forces the old shape, ?page=N /
                    # ?client=id fetch per-client detail on demand.
                    full = str(query.get("full", "")) \
                        in ("1", "true", "yes")
                    client = query.get("client")
                    page = None
                    try:
                        if query.get("page") is not None:
                            page = int(query["page"])
                    except (TypeError, ValueError):
                        page = None
                    big = (ctx.fleet.tracked_clients()
                           > obs.max_client_series)
                    snap = ctx.fleet.snapshot(
                        series=full or not big,
                        page=page, client=client)
                # aggregator-tree topology (aggregation.fan-in /
                # levels / remote): which node serves which group, so
                # straggler attribution can NAME a slow L1 instead of
                # pointing at "the aggregate phase"
                if ctx._agg_topology is not None:
                    snap["agg_tree"] = ctx._agg_topology
                # closed-loop scheduler view (runtime/scheduler.py):
                # the current online-cluster map and last re-plan
                # decision, plus per-client CLUSTER/SCHED fields so
                # straggler attribution can name WHY a client was
                # evicted/demoted (sl_top renders both columns)
                if ctx.scheduler is not None:
                    ctx.scheduler.annotate_fleet(snap)
                # sharded broker plane: per-shard stats rows (sl_top
                # ROLE=broker) — cached, so scrapes don't hammer the
                # shards' control queues
                brokers = _broker_stats()
                if brokers is not None:
                    snap["brokers"] = brokers
                return snap

            self.exporter = TelemetryExporter(
                _metrics, _fleet, port=int(obs.http_port),
                profile_fn=self.ctx.perf_capture.arm).start()
            self.log.info("telemetry: serving /metrics, /fleet and "
                          f"POST /profile on {self.exporter.url}",
                          "cyan")

    def serve(self) -> TrainResult:
        from split_learning_tpu.parallel.multihost import (
            ensure_initialized,
        )
        ensure_initialized()
        if self.cfg.pipeline.remote:
            # adopt stage hosts and deal the later-stage slots BEFORE
            # the registration barrier: the slots' inner clients are
            # the later-stage registrations the barrier counts, so no
            # host = the barrier can never release.  Zero adopted
            # hosts is therefore fatal, not a warning.
            ctx = self.ctx
            want = max(int(self.cfg.pipeline.hosts), 1)

            def helloed() -> int:
                return sum(1 for e in ctx._stage_hosts.values()
                           if "t" in e)
            ctx._pump_until(
                lambda: helloed() >= want,
                lambda: (f"stage host adoption "
                         f"({helloed()}/{want} helloed)"),
                deadline=time.monotonic() + 60.0)
            if not helloed():
                raise RoundTimeout(
                    "pipeline.remote: no stage host announced itself "
                    "within 60s — start hosts with `python -m "
                    "split_learning_tpu.stagehost` or set "
                    "pipeline.hosts")
            self.log.info(
                f"stage hosts adopted: {helloed()}/{want}", "cyan")
            ctx.assign_stage_slots()
        regs = self.ctx.wait_for_registrations()
        if self.cfg.aggregation.remote:
            # adopt aggregator nodes before the first round: spawned
            # subprocesses are still importing; externally-started
            # ones may hello any time.  A miss is a warning, not a
            # failure — the tree falls back to thread-mode L1s.
            ctx = self.ctx
            want = max(int(self.cfg.aggregation.nodes), 1)

            def adopted() -> int:
                return sum(1 for e in ctx._agg_nodes.values()
                           if "t" in e)
            ctx._pump_until(
                lambda: adopted() >= want,
                lambda: (f"aggregator node adoption "
                         f"({adopted()}/{want} helloed)"),
                deadline=time.monotonic() + 60.0)
            self.log.info(
                f"aggregator nodes adopted: {adopted()}/{want}",
                "cyan")
        # elastic deployments may have spares beyond the configured
        # counts at startup; plan whoever is there
        with self.ctx.tracer.span("plan"):
            plans = plan_clusters(
                self.cfg, regs,
                exact_counts=not self.cfg.topology.elastic_join)
        try:
            result = run_training(self.cfg, self.ctx, plans, self.log)
        finally:
            self.ctx.stop_all()
            from split_learning_tpu.runtime.perf import (
                process_capture, register_process_capture,
            )
            # only clear our own registration: a newer server in this
            # process may already have registered its capture
            if process_capture() is self.ctx.perf_capture:
                register_process_capture(None)
            if self.exporter is not None:
                self.exporter.close()
            for proc in self._spawned_nodes + self._spawned_hosts:
                # STOP already fanned out (stop_all); give each child
                # a moment to exit cleanly, then make sure
                try:
                    proc.wait(timeout=5.0)
                except Exception:  # noqa: BLE001 — still running
                    proc.terminate()
                    try:
                        proc.wait(timeout=5.0)
                    except Exception:  # noqa: BLE001 — last resort
                        proc.kill()
        return result


def main(argv=None):
    from split_learning_tpu.platform import apply_platform_env
    apply_platform_env()
    ap = argparse.ArgumentParser(
        description="Split-learning protocol server (reference server.py "
                    "parity).")
    ap.add_argument("--config", default="config.yaml")
    ap.add_argument("--broker", action="store_true",
                    help="also host the TCP broker in this process "
                         "(broker.shards > 1 hosts every shard of "
                         "the plane on consecutive ports)")
    ap.add_argument("--client_timeout", type=float, default=600.0)
    ap.add_argument("--ready_timeout", type=float, default=None,
                    help="registration/READY barrier deadline "
                         "(default: --client_timeout)")
    args = ap.parse_args(argv)
    cfg = from_yaml(args.config)
    from split_learning_tpu.platform import apply_compile_cache
    apply_compile_cache(cfg.compile_cache_dir)
    blackbox.install(cfg, "server", role="server")
    brokers = []
    if args.broker and cfg.transport.kind == "tcp":
        # each shard is its own O(1)-thread event loop; hosting N of
        # them in-process keeps the single-command dev deployment
        # working with broker.shards > 1 (production runs them as
        # separate processes: python -m split_learning_tpu.broker
        # --shards N)
        brokers = [Broker(cfg.transport.host, cfg.transport.port + i,
                          shard_id=f"shard_{i}")
                   for i in range(cfg.broker.shards)]
    try:
        server = ProtocolServer(cfg, client_timeout=args.client_timeout,
                                ready_timeout=args.ready_timeout)
        result = server.serve()
        for rec in result.history:
            acc = (f" val_acc={rec.val_accuracy:.4f}"
                   if rec.val_accuracy is not None else "")
            print(f"round {rec.round_idx}: ok={rec.ok} "
                  f"samples={rec.num_samples}{acc}")
    finally:
        for broker in brokers:
            broker.close()


if __name__ == "__main__":
    main()
