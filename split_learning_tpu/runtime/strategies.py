"""Round strategies: the reference's six server forks as plug-ins.

The reference implements each scheduling/aggregation algorithm as a full
copy of the server (SURVEY.md §2.3): the main concurrent FedAvg server
(``/root/reference/src/Server.py``), Vanilla_SL's sequential relay,
Cluster_FSL's cluster relay, FLEX's periodic aggregation, 2LS's two-level
FedAsync, and DCSL's round-robin SDA.  Here each is a
:class:`RoundStrategy` driving the same :class:`TrainContext` — host
Python decides *who trains when* and *how weights merge*; the compiled
mesh step never changes.

Aggregation math is shared: per-cluster per-stage weighted FedAvg
(``src/Server.py:398-408`` → ``src/Utils.py:35-66``), stage concatenation
(disjoint absolute layer keys), unweighted cross-cluster average
(``:410-434``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Sequence

import jax
import numpy as np

from split_learning_tpu.config import Config
from split_learning_tpu.ops.fedavg import TreeFold, fedavg_trees
from split_learning_tpu.runtime.context import TrainContext
from split_learning_tpu.runtime.plan import ClusterPlan
from split_learning_tpu.runtime.protocol import Update


def _span(ctx, name: str, **attrs):
    """Tracing span on the context's tracer (no-op without one)."""
    tracer = getattr(ctx, "tracer", None)
    if tracer is None:
        return contextlib.nullcontext()
    return tracer.span(name, **attrs)


@dataclasses.dataclass
class RoundOutcome:
    params: Any
    stats: Any
    ok: bool = True
    num_samples: int = 0
    validate: bool = True           # run full-model validation this round?
    metrics: dict = dataclasses.field(default_factory=dict)


# --------------------------------------------------------------------------
# shared aggregation math
# --------------------------------------------------------------------------

def aggregate_cluster(updates: Sequence[Update]) -> tuple[Any, Any, int]:
    """Per-stage weighted FedAvg then stage concat for ONE cluster.

    Returns (params_tree, stats_tree, total_stage1_samples).

    When the protocol server already folded the round incrementally
    (``aggregation.streaming``, ``runtime/aggregate.py``), ``updates``
    arrives as an :class:`~split_learning_tpu.runtime.aggregate.
    UpdateBatch` whose ``fold`` member carries the finished
    :class:`~split_learning_tpu.runtime.aggregate.FoldResult` — the
    per-client trees were folded (and freed) the moment each UPDATE
    decoded, so this function just unwraps the result instead of
    re-folding.  Otherwise it runs the **reference oracle**: the
    barrier fold the streaming plane is proven bit-identical against
    in tests, itself streamed per stage through
    :class:`~split_learning_tpu.ops.fedavg.TreeFold` (one contributor
    tree + the accumulator in flight — never a list of full trees,
    slcheck AG001).

    Delta-encoded updates (``transport.codec`` rpc family) must be
    reconstructed against the server's versioned shadow BEFORE they
    reach this fold (``runtime/server.py _fold_update``) — averaging a
    delta as if it were a weight tree would corrupt the global model
    silently, so an un-reconstructed one is a hard error here.
    Weight-less updates (FLEX non-aggregation rounds, streamed rounds
    whose trees already folded, or a delta whose version chain broke
    and was stripped) carry no tree to fold and are skipped; their
    samples still count toward the round total."""
    fold = getattr(updates, "fold", None)
    by_stage: dict[int, list[Update]] = {}
    n_weightless = 0
    # dedup on (client_id, version) BEFORE any sample accounting: an
    # at-least-once transport can redeliver a client's Update after the
    # streaming fold already consumed (and weight-stripped) the first
    # copy — without this guard the weight-less skip path would count
    # the same client's samples twice (PR 6 regression)
    seen: set = set()
    for u in updates:
        if getattr(u, "delta_base", None) is not None:
            raise ValueError(
                f"delta-encoded Update from {u.client_id} (base "
                f"v{u.delta_base}) reached aggregation un-reconstructed")
        key = (u.client_id,
               u.version if getattr(u, "version", None) is not None
               else u.round_idx)
        if key in seen:
            continue
        seen.add(key)
        if fold is not None or u.params is None:
            if u.stage == 1:
                n_weightless += u.num_samples
            continue
        by_stage.setdefault(u.stage, []).append(u)
    if fold is not None:
        # the streamed result IS the barrier fold (bit-identical by
        # the canonical-order contract); its own sample count already
        # includes every stage-1 contribution
        return fold.params, fold.stats, fold.n_samples
    params: dict = {}
    stats: dict = {}
    n_samples = n_weightless   # trained samples count even when the
    # weights were stripped (broken delta chain) — the round's data
    # throughput is real; only the fold skips the client
    for stage, ups in sorted(by_stage.items()):
        # client-id order, not arrival order: float summation order must
        # not depend on which UPDATE won a thread race, or two identical
        # rounds (e.g. a chaos run vs its fault-free twin) diverge in
        # the last bits
        ups = sorted(ups, key=lambda u: u.client_id)
        pfold, sfold = TreeFold(), TreeFold()
        for u in ups:
            w = max(1, u.num_samples)
            pfold.add(u.params, w)
            if u.batch_stats:
                sfold.add(u.batch_stats, w)
        params.update(pfold.finalize())
        if sfold.total_w:
            stats.update(sfold.finalize())
        if stage == 1:
            n_samples += sum(u.num_samples for u in ups)
    return params, stats, n_samples


def merge_clusters(cluster_trees: Sequence[Any]) -> Any:
    """Unweighted cross-cluster average (``src/Server.py:410-434``).

    Deliberately NOT short-circuited for one cluster: the degenerate
    average still runs every leaf through ``nan_to_num`` — relay-style
    strategies feed RAW client trees in here, and that sanitization is
    load-bearing for them.  The FedAvg/SDA round path (whose single
    tree comes out of the already-sanitized fold) skips this call at
    the call site instead."""
    return fedavg_trees(list(cluster_trees))


def _lerp(a: Any, b: Any, alpha: float) -> Any:
    """(1-alpha)*a + alpha*b elementwise over matching pytrees."""
    return jax.tree_util.tree_map(
        lambda x, y: np.asarray((1.0 - alpha) * np.asarray(x, np.float32)
                                + alpha * np.asarray(y, np.float32),
                                dtype=np.asarray(x).dtype), a, b)


def _fill(full: Any, partial: Any) -> Any:
    """Overlay aggregated layers onto the previous full tree (clusters with
    fewer stages than layers exist only in degenerate configs; missing keys
    keep their previous values — the reference's checkpoint-merge
    semantics, ``src/Server.py:230-256``)."""
    out = dict(full)
    out.update(partial)
    return out


# --------------------------------------------------------------------------
# strategies
# --------------------------------------------------------------------------

class RoundStrategy:
    name = "base"

    def __init__(self, cfg: Config):
        self.cfg = cfg

    def run_round(self, ctx: TrainContext, plans: list[ClusterPlan],
                  round_idx: int, params: Any, stats: Any) -> RoundOutcome:
        raise NotImplementedError

    def _lr(self, round_idx: int) -> float | None:
        """DCSL-style lr decay (``other/DCSL/src/Server.py:38-39``)."""
        lrn = self.cfg.learning
        if lrn.lr_decay_every and lrn.lr_decay != 1.0:
            return lrn.learning_rate * (
                lrn.lr_decay ** (round_idx // lrn.lr_decay_every))
        return None


class FedAvgStrategy(RoundStrategy):
    """Main-server behavior: all clusters train concurrently, per-cluster
    FedAvg per stage, cross-cluster average, validate every round
    (``src/Server.py:155-210``)."""
    name = "fedavg"
    sync_all_later_stages = False   # SDA override

    def _epochs(self) -> int:
        return 1

    def run_round(self, ctx, plans, round_idx, params, stats):
        if len(plans) == 1:
            # device-resident fast path (MeshContext, plain FedAvg
            # geometry): weights stay on the mesh between rounds, the
            # round barrier is an on-mesh weighted psum — numerically
            # the same fold, none of the per-round host<->device traffic
            resident = getattr(ctx, "train_cluster_resident", None)
            if resident is not None:
                res = resident(
                    plans[0], params, stats, round_idx=round_idx,
                    epochs=self._epochs(), lr=self._lr(round_idx),
                    sync_all_later_stages=self.sync_all_later_stages)
                if res is not None:
                    if not res.ok:
                        return RoundOutcome(params, stats, ok=False,
                                            validate=False)
                    return RoundOutcome(
                        res.params, res.stats,
                        num_samples=res.num_samples,
                        metrics=getattr(res, "timings", {}) or {})
        cluster_params, cluster_stats = [], []
        total, ok = 0, True
        agg_s = 0.0
        for plan in plans:
            ups = ctx.train_cluster(
                plan, params, stats, round_idx=round_idx,
                epochs=self._epochs(), lr=self._lr(round_idx),
                sync_all_later_stages=self.sync_all_later_stages)
            ok &= all(u.ok for u in ups)
            t0 = time.perf_counter()
            p, s, n = aggregate_cluster(ups)
            agg_s += time.perf_counter() - t0
            cluster_params.append(_fill(params, p))
            cluster_stats.append(_fill(stats, s))
            total += n
        if not ok:
            # reference: round_result False -> skip aggregation entirely
            # (src/Server.py:162-166, :195-196)
            return RoundOutcome(params, stats, ok=False, validate=False)
        # the round's FedAvg fold as one "aggregate" span (round-phase
        # attribution for the critical-path report); timestamp-shifted
        # spans would misplace the per-cluster folds, so the merged
        # span covers the final merge and carries the fold total
        with _span(ctx, "aggregate", round=round_idx,
                   fold_s=round(agg_s, 6)):
            if len(plans) == 1:
                # one cluster (the common deployment): the tree IS the
                # fold's output — already nan_to_num-sanitized by the
                # fold's contribution path — so the degenerate
                # self-average would only re-materialize every leaf on
                # the round path, defeating the sharded update's
                # one-fetch-per-stage discipline (the next START
                # fan-out and delta shadow slice these arrays in place)
                out = RoundOutcome(cluster_params[0], cluster_stats[0],
                                   num_samples=total)
            else:
                out = RoundOutcome(merge_clusters(cluster_params),
                                   merge_clusters(cluster_stats),
                                   num_samples=total)
        return out


class SDAStrategy(FedAvgStrategy):
    """DCSL: later stages train on concatenated client batches (full
    client-axis gradient sync) for ``local_rounds`` epochs per round
    (``other/DCSL/src/Scheduler.py:152-191``, ``:83``)."""
    name = "sda"
    sync_all_later_stages = True

    def _epochs(self) -> int:
        return self.cfg.aggregation.local_rounds


class RelayStrategy(RoundStrategy):
    """Vanilla_SL: stage-1 clients train ONE AT A TIME; each finisher's
    stage-1 weights seed the next client; later stages train continuously
    (``other/Vanilla_SL/src/Server.py:130-146``, ``:248-268``)."""
    name = "relay"

    def run_round(self, ctx, plans, round_idx, params, stats):
        total, ok = 0, True
        cluster_params, cluster_stats = [], []
        for plan in plans:
            cur_p, cur_s = params, stats
            last_stage_updates: list[Update] = []
            for cid in plan.stage1_clients:
                ups = ctx.train_cluster(plan, cur_p, cur_s,
                                        round_idx=round_idx,
                                        client_subset=[cid],
                                        lr=self._lr(round_idx))
                ok &= all(u.ok for u in ups)
                for u in ups:
                    cur_p = _fill(cur_p, u.params)
                    if u.batch_stats:
                        cur_s = _fill(cur_s, u.batch_stats)
                    if u.stage == 1:
                        total += u.num_samples
                    else:
                        last_stage_updates.append(u)
            # final FedAvg across the relay's later-stage snapshots
            # (other/Vanilla_SL/src/Server.py: stage-2 devices averaged at
            # round end)
            if last_stage_updates:
                p, s, _ = aggregate_cluster(last_stage_updates)
                cur_p = _fill(cur_p, p)
                if s:
                    cur_s = _fill(cur_s, s)
            cluster_params.append(cur_p)
            cluster_stats.append(cur_s)
        if not ok:
            return RoundOutcome(params, stats, ok=False, validate=False)
        return RoundOutcome(merge_clusters(cluster_params),
                            merge_clusters(cluster_stats),
                            num_samples=total)


class ClusterRelayStrategy(RoundStrategy):
    """Cluster_FSL: clusters run sequentially; cluster i's aggregated
    stage-1 weights initialize cluster i+1; later stages carry over
    continuously (``other/Cluster_FSL/src/Server.py:151-167``,
    ``:267-288``)."""
    name = "cluster_relay"

    def run_round(self, ctx, plans, round_idx, params, stats):
        cur_p, cur_s = params, stats
        total, ok = 0, True
        for plan in plans:
            ups = ctx.train_cluster(plan, cur_p, cur_s,
                                    round_idx=round_idx,
                                    lr=self._lr(round_idx))
            ok &= all(u.ok for u in ups)
            p, s, n = aggregate_cluster(ups)
            cur_p = _fill(cur_p, p)
            cur_s = _fill(cur_s, s)
            total += n
        if not ok:
            return RoundOutcome(params, stats, ok=False, validate=False)
        return RoundOutcome(cur_p, cur_s, num_samples=total)


class PeriodicStrategy(RoundStrategy):
    """FLEX: per-client weights PERSIST across rounds; client-level FedAvg
    every ``t_client`` rounds, global merge + validation every ``t_global``
    rounds (``other/FLEX/src/Server.py:169-183``, ``:200-208``).

    Wire economy over the protocol backend (contexts with
    ``clients_hold_state``): on non-aggregation rounds clients neither
    receive weights in START nor upload them in UPDATE — the PAUSE
    ``send`` flag and param-less START of
    ``other/FLEX/src/Server.py:140-143``/``:220-226``.  Stage-1 clients
    upload on ``t_client`` and ``t_global`` boundaries; later stages only
    on ``t_global`` (``client_send``/``edge_send``).  In-process mesh
    contexts rebuild client state every round, so there the strategy
    re-pushes persisted trees each round (no wire to economize).
    """
    name = "periodic"

    def __init__(self, cfg):
        super().__init__(cfg)
        self._client_params: dict = {}   # client_id -> full tree
        self._reseed_stages: set = {0}   # 0 = every stage (initial seed)

    def run_round(self, ctx, plans, round_idx, params, stats):
        agg = self.cfg.aggregation
        hold = getattr(ctx, "clients_hold_state", False)
        boundary_c = (round_idx + 1) % agg.t_client == 0
        boundary_g = (round_idx + 1) % agg.t_global == 0
        total, ok = 0, True
        cluster_params, cluster_stats = [], []
        for plan in plans:
            if hold:
                send_w = {s: (boundary_c or boundary_g) if s == 1
                          else boundary_g
                          for s in range(1, plan.n_stages + 1)}
                send_p = {s: (0 in self._reseed_stages
                              or s in self._reseed_stages)
                          for s in range(1, plan.n_stages + 1)}
            else:
                send_w = send_p = True
            ups = ctx.train_cluster(
                plan, params, stats, round_idx=round_idx,
                per_client_params=dict(self._client_params),
                lr=self._lr(round_idx),
                send_params=send_p, send_weights=send_w)
            ok &= all(u.ok for u in ups)
            for u in ups:
                if u.stage == 1:
                    total += u.num_samples
            # persist each uploading client's full tree (its shard
            # overlaid on the round's base); weight-less updates (FLEX
            # non-aggregation rounds) persist nothing
            got_w = [u for u in ups if u.params is not None]
            for u in got_w:
                base = self._client_params.get(u.client_id, params)
                # FLEX client-level persistence IS the strategy (one
                # bounded tree per stage-1 client, not a round-path
                # accumulation)
                self._client_params[u.client_id] = _fill(base, u.params)  # slcheck: agg-state
            if got_w:
                p, s, _ = aggregate_cluster(got_w)
                cluster_params.append(_fill(params, p))
                cluster_stats.append(_fill(stats, s))
            if boundary_c and not boundary_g and got_w:
                # client-level FedAvg: reset the cluster's stage-1
                # clients to the cluster average
                # (other/FLEX/src/Server.py:169-183)
                for cid in plan.stage1_clients:
                    self._client_params[cid] = cluster_params[-1]
        if not ok:
            self._reseed_stages = {0}   # deterministic recovery re-seed
            return RoundOutcome(params, stats, ok=False, validate=False)
        self._reseed_stages = set()
        if boundary_g:
            merged = merge_clusters(cluster_params)
            merged_stats = merge_clusters(cluster_stats)
            self._client_params.clear()  # re-seed everyone from global
            self._reseed_stages = {0}
            return RoundOutcome(merged, merged_stats, num_samples=total,
                                validate=True)
        if boundary_c:
            self._reseed_stages = {1}
        return RoundOutcome(params, stats, num_samples=total,
                            validate=False)


class FedAsyncStrategy(RoundStrategy):
    """2LS two-level clustering + FedAsync
    (``other/2LS/src/Server.py:170-233``).

    Out-clusters (the ``plans``) execute sequentially in shuffled order
    per round.  Within an out-cluster, ``topology.in_clusters``
    in-clusters — contiguous groups of stage-1 clients, each paired with
    a stage-2 head (``other/2LS/client.py:15-17``) — train
    concurrently; each in-cluster's 2-stage average then merges into the
    global model in completion order with ``alpha = 1/(1+rank)`` (or the
    fixed config alpha): ``g = (1-a) g + a c``.  Rank resets per
    out-cluster, so the first in-cluster's average replaces the global
    (``fed_async_aggregate`` with ``alpha=1``) — continuity across
    out-clusters flows through the training init, reference-faithfully.
    ``in_clusters=1`` degenerates to one merge per out-cluster.

    When head counts don't match ``in_clusters`` the protocol backend
    keeps shared forward queues (no fixed pairing on the wire;
    ``runtime/server.py`` logs it) while aggregation still partitions
    updates round-robin over the in-groups — every update counted
    exactly once, merge order as configured.
    """
    name = "fedasync"

    def _in_groups(self, plan: ClusterPlan) -> list[tuple[list, set]]:
        """[(stage1_member_ids, later_stage_member_ids)] per in-cluster.

        Later-stage clients are PARTITIONED over the in-clusters
        round-robin, so every update belongs to exactly one in-cluster
        (1:1 pairing when counts match — the reference topology; with
        ``in_clusters=1`` every client lands in the single group,
        reducing to a whole-cluster average)."""
        from split_learning_tpu.runtime.context import client_groups
        n_in = max(1, self.cfg.topology.in_clusters)
        s1 = plan.stage1_clients
        groups = client_groups(len(s1), min(n_in, len(s1)))
        later: list[set] = [set() for _ in groups]
        for s in range(2, plan.n_stages + 1):
            for j, cid in enumerate(plan.clients[s - 1]):
                later[j % len(groups)].add(cid)
        return [([s1[i] for i in idxs], later[g])
                for g, idxs in enumerate(groups)]

    def run_round(self, ctx, plans, round_idx, params, stats):
        rng = np.random.default_rng(self.cfg.seed + round_idx)
        order = rng.permutation(len(plans))
        g_p, g_s = params, stats
        total, ok = 0, True
        saved_any = False   # any per-merge checkpoint written this round
        for pi in order:
            plan = plans[pi]
            ups = ctx.train_cluster(plan, g_p, g_s, round_idx=round_idx,
                                    lr=self._lr(round_idx))
            ok &= all(u.ok for u in ups)
            rank = 0   # over REPORTING in-clusters only: the reference
            # enumerates check_in_cluster (groups that actually finished,
            # other/2LS/src/Server.py:178-184), so a dropped in-cluster
            # must not shift the survivors' alphas
            for members, later in self._in_groups(plan):
                in_ups = [u for u in ups
                          if (u.stage == 1 and u.client_id in members)
                          or (u.stage >= 2 and u.client_id in later)]
                if not in_ups:
                    continue
                p, s, n = aggregate_cluster(in_ups)
                alpha = (self.cfg.aggregation.fedasync_alpha
                         if self.cfg.aggregation.fedasync_alpha is not None
                         else 1.0 / (1.0 + rank))
                rank += 1
                g_p = _lerp(g_p, _fill(g_p, p), alpha)
                g_s = _fill(g_s, s)
                total += n
                if (ok and self.cfg.checkpoint.per_merge
                        and self.cfg.checkpoint.save):
                    # 2LS persists every alpha-merge
                    # (other/2LS/src/Server.py:184): a crash mid-round
                    # then loses at most one in-cluster's work.
                    # Synchronous like the reference — per-merge
                    # durability is the point; don't trade it for
                    # overlap.  Gated on `ok` so far: once any update
                    # was NaN-flagged the round will revert, and a
                    # tainted merge must not overwrite the last good
                    # checkpoint on disk (the round loop only saves
                    # rec.ok rounds — same contract here)
                    from split_learning_tpu.runtime.checkpoint import (
                        save_checkpoint,
                    )
                    save_checkpoint(self.cfg.checkpoint.directory,
                                    self.cfg.model_key, g_p, g_s,
                                    round_idx=round_idx)
                    saved_any = True
        if not ok:
            if saved_any:
                # a LATER plan's NaN reverts the round, but earlier
                # clean merges already overwrote the checkpoint — put
                # the round-entry state back so a crash never resumes
                # from a state the run rejected
                from split_learning_tpu.runtime.checkpoint import (
                    save_checkpoint,
                )
                save_checkpoint(self.cfg.checkpoint.directory,
                                self.cfg.model_key, params, stats,
                                round_idx=round_idx)
            return RoundOutcome(params, stats, ok=False, validate=False)
        return RoundOutcome(g_p, g_s, num_samples=total)


_STRATEGIES = {
    cls.name: cls for cls in (
        FedAvgStrategy, SDAStrategy, RelayStrategy, ClusterRelayStrategy,
        PeriodicStrategy, FedAsyncStrategy)
}


def make_strategy(cfg: Config) -> RoundStrategy:
    name = cfg.aggregation.strategy
    if name not in _STRATEGIES:
        raise ValueError(f"unknown strategy {name!r}; "
                         f"known: {sorted(_STRATEGIES)}")
    return _STRATEGIES[name](cfg)
