"""Tiled absmax int8/int4 quantization — the activation/gradient wire
codec (``transport.codec: {intermediate: int8, ...}``).

The quantizer runs ON DEVICE, before the device→host fetch (slcheck
JX002 discipline: the PCIe/ICI hop moves quantized bytes, not fp32):
per-tile absmax scales are computed by a jitted kernel, int4 codes are
nibble-packed on device, and only the code array + the (tiny) scale
vector cross to host.  Dequantization is the mirror jitted kernel on
the receiver, so neither endpoint touches fp32 payload bytes on the
hot path.

Numerics: ``x ≈ q * scale`` per tile with ``scale = max|x| / qmax``
(qmax 127 for int8, 7 for int4).  An all-zero tile uses scale 1 (any
scale dequantizes zeros exactly); a NON-FINITE tile ships a NaN scale
so the diverged values survive the hop and the receiver's NaN sentinel
(``src/train/VGG16.py:169-171``) still fires — per tile, so one NaN no
longer forces the whole leaf back to raw fp32 the way the legacy
per-tensor int8 wire dtype did.

A numpy twin of each kernel (``quantize_np``/``dequantize_leaf_np``)
serves the once-per-round Update/delta path, whose payloads are
already host-side; the hot data plane must use the device half (the
``codec`` slcheck analyzer flags host quantization inside tick loops).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.runtime.codec.specs import CodecSpec
from split_learning_tpu.runtime.protocol import QuantLeaf


class DevQuant:
    """Device-staged quantized leaf: codes + scales still on device so
    ``copy_to_host_async`` can prefetch them; the async sender's encode
    thunk turns it into a wire :class:`QuantLeaf`.  Registered as a
    pytree (unlike QuantLeaf) so ``_start_host_copy``/``tree_map`` walk
    into the device arrays."""

    def __init__(self, q: Any, scale: Any, bits: int, tile: int,
                 shape: tuple):
        self.q = q
        self.scale = scale
        self.bits = bits
        self.tile = tile
        self.shape = tuple(int(s) for s in shape)


jax.tree_util.register_pytree_node(
    DevQuant,
    lambda d: ((d.q, d.scale), (d.bits, d.tile, d.shape)),
    lambda aux, ch: DevQuant(ch[0], ch[1], *aux))


def _qmax(bits: int) -> float:
    return 127.0 if bits == 8 else 7.0


@functools.partial(jax.jit, static_argnames=("tile", "bits",
                                             "kernel_block"))
def _quantize_dev(x, tile: int, bits: int, kernel_block: int = 0):
    """(codes, per-tile scales) for one float leaf, on device.

    Codes are the FLAT padded array: int8 for bits=8; for bits=4 two
    two's-complement nibbles packed per uint8 byte (lo nibble first).
    ``kernel_block > 0`` routes the tiled math through the fused Pallas
    kernel (``ops/kernels/quant.py``) — one VMEM-resident pass instead
    of this chain of full-leaf HBM round-trips; the pad/reshape
    prologue stays here either way."""
    qmax = _qmax(bits)
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = (-n) % tile
    # int4 packs code pairs: the padded count must also be even (an odd
    # tile width can leave it odd — one more tile of zeros fixes both)
    if bits == 4 and (n + pad) % 2:
        pad += tile
    flat = jnp.pad(flat, (0, pad))
    tiles = flat.reshape(-1, tile)
    if kernel_block:
        from split_learning_tpu.ops.kernels.quant import quantize_tiles
        return quantize_tiles(tiles, bits=bits, block=kernel_block)
    amax = jnp.max(jnp.abs(tiles), axis=1)
    scale = jnp.where(jnp.isfinite(amax),
                      jnp.where(amax > 0, amax / qmax, 1.0),
                      jnp.nan).astype(jnp.float32)
    codes = jnp.clip(jnp.round(tiles / scale[:, None]), -qmax, qmax)
    # NaN codes (non-finite tile: scale is NaN) become 0 — the NaN
    # scale alone carries the divergence, and int8-casting NaN would be
    # platform-defined where everything else here is deterministic
    q = jnp.where(jnp.isfinite(codes), codes, 0.0).astype(jnp.int8)
    q = q.reshape(-1)
    if bits == 4:
        u = q.astype(jnp.uint8) & 0xF      # two's-complement nibble
        q = (u[0::2] | (u[1::2] << 4)).astype(jnp.uint8)
    return q, scale


@functools.partial(jax.jit, static_argnames=("tile", "bits", "n",
                                             "shape", "kernel_block"))
def _dequantize_dev(q, scale, tile: int, bits: int, n: int,
                    shape: tuple, kernel_block: int = 0):
    # the fused mirror kernel applies only to well-formed tiled codes
    # (exactly scale.count * tile codes — what OUR quantizers emit);
    # anything ragged keeps the legacy XLA chain below
    expect = scale.shape[0] * tile // (2 if bits == 4 else 1)
    if kernel_block and q.shape[0] == expect:
        from split_learning_tpu.ops.kernels.quant import (
            dequantize_tiles,
        )
        out = dequantize_tiles(q, scale, tile=tile, bits=bits,
                               block=kernel_block)
        return out[:n].reshape(shape)
    if bits == 4:
        u = q.astype(jnp.uint8)
        lo, hi = u & 0xF, u >> 4
        codes = jnp.stack([lo, hi], axis=-1).reshape(-1)
        codes = jnp.where(codes < 8, codes,
                          codes.astype(jnp.int32) - 16)
    else:
        codes = q
    flat = codes.astype(jnp.float32)
    padded = jnp.pad(flat, (0, (-flat.shape[0]) % tile)) \
        if flat.shape[0] % tile else flat
    out = (padded.reshape(-1, tile)
           * scale[:, None]).reshape(-1)[:n]
    return out.reshape(shape)


class QuantCodec:
    """Per-family activation/gradient quantizer (stateless)."""

    name = "quant"
    COUNTERS = ("quant_nonfinite",)

    def __init__(self, spec: CodecSpec, faults=None, kernels=None):
        self.bits = spec.bits
        self.tile = spec.tile
        # explicit kernel plan wins; None defers to the process-wide
        # plan at prepare time (ops/kernels.configure — installed by
        # make_codecs from the loaded config)
        self._kernels = kernels
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults

    def _kernel_block(self) -> int:
        from split_learning_tpu.ops import kernels as kplane
        kp = kplane.as_plan(self._kernels)
        return kp.block if kp.quantize else 0

    def prepare(self, tree, key: str = ""):
        """Device-side stage (training thread): float leaves become
        :class:`DevQuant` holders; int/bool leaves pass through."""
        kb = self._kernel_block()

        def conv(leaf):
            ldt = getattr(leaf, "dtype", None)
            if (ldt is None or ldt == jax.dtypes.float0
                    or not jnp.issubdtype(ldt, jnp.floating)):
                return leaf
            x = jnp.asarray(leaf)
            q, scale = _quantize_dev(x, self.tile, self.bits,
                                     kernel_block=kb)
            return DevQuant(q, scale, self.bits, self.tile, x.shape)
        return jax.tree_util.tree_map(
            conv, tree, is_leaf=lambda o: isinstance(o, DevQuant))

    def encode(self, prepared):
        """Host-side stage (async sender thread): fetch the staged
        device arrays and build wire :class:`QuantLeaf` leaves."""
        def conv(leaf):
            if isinstance(leaf, DevQuant):
                scale = np.asarray(leaf.scale)
                if not np.isfinite(scale).all():
                    # a diverged payload crossed the wire: visible in
                    # the counters, not just in the eventual NaN loss
                    self.faults.inc("quant_nonfinite")
                return QuantLeaf(q=np.asarray(leaf.q), scale=scale,
                                 bits=leaf.bits, tile=leaf.tile,
                                 shape=leaf.shape)
            if getattr(leaf, "dtype", None) == jax.dtypes.float0:
                return np.zeros(np.shape(leaf), np.float32)
            return np.asarray(leaf)
        return jax.tree_util.tree_map(
            conv, prepared, is_leaf=lambda o: isinstance(o, DevQuant))


def dequantize_leaf(leaf: QuantLeaf, kernels=None):
    """Wire QuantLeaf -> device float32 array (receiver hot path).

    Handles both generations: the legacy per-tensor scalar-scale form
    keeps its exact original computation (bit parity with the int8
    wire-dtype path), the tiled form runs the jitted kernel.  Decode is
    self-describing (no sender config in scope), so the fused Pallas
    mirror engages through the RECEIVER's kernel plan — the explicit
    ``kernels`` argument, or the process-wide plan."""
    if leaf.tile == 0 and leaf.shape is None:
        return jnp.asarray(leaf.q, jnp.float32) * np.float32(leaf.scale)
    from split_learning_tpu.ops import kernels as kplane
    kp = kplane.as_plan(kernels)
    kb = kp.block if kp.dequantize else 0
    n = int(np.prod(leaf.shape)) if leaf.shape else 1
    return _dequantize_dev(jnp.asarray(leaf.q), jnp.asarray(leaf.scale),
                           leaf.tile, leaf.bits, n, tuple(leaf.shape),
                           kernel_block=kb)


# -- numpy twins (once-per-round Update/delta path; host-side inputs) ------

def quantize_np(x: np.ndarray, tile: int, bits: int) -> QuantLeaf:
    qmax = _qmax(bits)
    flat = np.asarray(x, np.float32).reshape(-1)
    n = flat.size
    pad = (-n) % tile
    if bits == 4 and (n + pad) % 2:
        pad += tile   # keep tile alignment AND an even code count
    padded = np.pad(flat, (0, pad))
    tiles = padded.reshape(-1, tile)
    amax = np.max(np.abs(tiles), axis=1)
    with np.errstate(invalid="ignore"):
        scale = np.where(np.isfinite(amax),
                         np.where(amax > 0, amax / qmax, 1.0),
                         np.nan).astype(np.float32)
        q = np.clip(np.round(tiles / scale[:, None]), -qmax,
                    qmax)
    q = np.nan_to_num(q, nan=0.0).astype(np.int8).reshape(-1)
    if bits == 4:
        u = (q.astype(np.uint8) & 0xF)
        q = (u[0::2] | (u[1::2] << 4)).astype(np.uint8)
    return QuantLeaf(q=q, scale=scale, bits=bits, tile=tile,
                     shape=tuple(int(s) for s in np.shape(x)))


def dequantize_leaf_np(leaf: QuantLeaf) -> np.ndarray:
    if leaf.tile == 0 and leaf.shape is None:
        return np.asarray(leaf.q, np.float32) * np.float32(leaf.scale)
    if leaf.bits == 4:
        u = np.asarray(leaf.q, np.uint8)
        lo, hi = u & 0xF, u >> 4
        codes = np.stack([lo, hi], axis=-1).reshape(-1).astype(np.int32)
        codes = np.where(codes < 8, codes, codes - 16)
    else:
        codes = np.asarray(leaf.q, np.int32)
    flat = codes.astype(np.float32)
    if flat.size % leaf.tile:
        flat = np.pad(flat, (0, (-flat.size) % leaf.tile))
    scale = np.asarray(leaf.scale, np.float32)
    n = int(np.prod(leaf.shape)) if leaf.shape else 1
    out = (flat.reshape(-1, leaf.tile) * scale[:, None]).reshape(-1)[:n]
    return out.reshape(leaf.shape)
