"""Codec spec grammar — the import-light half of the codec package.

``transport.codec`` maps a queue FAMILY to a codec spec string::

    transport:
      codec:
        intermediate: int8          # tiled absmax int8 activations
        gradient: topk:0.05         # top-5% gradients + error feedback
        rpc: delta:int8             # int8-quantized Update deltas

This module owns parsing + validation of those strings and the static
metadata the ``codec`` slcheck analyzer consumes (which counters each
codec kind may increment).  It deliberately imports NOTHING heavy:
``config.py`` validates specs at YAML-load time and the analyzer runs
in ``--no-trace`` (jax-free) CI lanes — both must not pull in jax.

Spec grammar (kind[:arg[:arg]]):

* ``int8`` / ``int4``            — tiled absmax quantization; optional
  ``:<tile>`` sets the per-tile scale width (elements; default 256),
  e.g. ``int4:128``.
* ``topk:<frac>``                — magnitude top-k sparsification with a
  client-side error-feedback residual; ``frac`` in (0, 1] is the kept
  fraction, e.g. ``topk:0.05``.
* ``delta`` / ``delta:int8[:t]`` / ``delta:bf16``
  — Update frames carry ``params - last_server_acked`` against a
  version tag; the payload delta ships bf16 (default) or tiled-int8.

Family compatibility: ``intermediate`` takes quantizers, ``gradient``
takes quantizers or topk, ``rpc`` takes delta only — a spec outside its
family is a config error, not a silent no-op.
"""

from __future__ import annotations

import dataclasses

#: queue families a codec policy can target (the protocol's four
#: tensor-framed planes: Activation, Gradient, Update, and the
#: aggregator tree's PartialAggregate sums)
CODEC_FAMILIES = ("intermediate", "gradient", "rpc", "partial")

#: codec kind -> FaultCounters names its runtime half may increment.
#: The ``codec`` slcheck analyzer (CD001) holds every entry to the
#: declared registries in ``runtime/trace.py`` — a codec minting an
#: unregistered counter is a typo no dashboard would ever surface.
CODEC_COUNTERS: dict[str, tuple] = {
    # the partial family reuses the int8/int4/delta kinds
    # (runtime/codec/partial.py): undecodable codec'd partials count
    # partial_codec_errors at the receiving aggregator/root
    "int8": ("quant_nonfinite", "partial_codec_errors"),
    "int4": ("quant_nonfinite", "partial_codec_errors"),
    "topk": ("topk_dense_fallbacks",),
    "delta": ("delta_folds", "delta_full_frames", "delta_resyncs",
              "quant_nonfinite", "partial_codec_errors"),
}

#: specs legal per family.  ``partial`` (the aggregator tree's
#: PartialAggregate sums, ``runtime/codec/partial.py``) takes the
#: tiled quantizers — the group MEAN ships as int8/int4 codes — or
#: ``delta[:int8[:tile]]``: mean minus the generation's START shard
#: (the base both endpoints hold), quantized.
_FAMILY_KINDS = {
    "intermediate": ("int8", "int4"),
    "gradient": ("int8", "int4", "topk"),
    "rpc": ("delta",),
    "partial": ("int8", "int4", "delta"),
}

DEFAULT_TILE = 256


class CodecSpecError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class CodecSpec:
    """One parsed codec spec."""
    kind: str                     # int8 | int4 | topk | delta
    bits: int = 8                 # quantizer width (int8/int4/delta:int8)
    tile: int = DEFAULT_TILE      # per-tile scale width (elements)
    frac: float = 0.0             # topk kept fraction
    delta_dtype: str = ""         # delta payload: "bfloat16" | "int8"


def _parse_tile(tok: str, spec: str) -> int:
    try:
        tile = int(tok)
    except ValueError:
        raise CodecSpecError(
            f"codec spec {spec!r}: tile must be an integer, "
            f"got {tok!r}") from None
    if tile < 1:
        raise CodecSpecError(f"codec spec {spec!r}: tile must be >= 1")
    return tile


def parse_spec(spec: str) -> CodecSpec:
    """Parse one codec spec string; :class:`CodecSpecError` on junk."""
    if not isinstance(spec, str) or not spec:
        raise CodecSpecError(f"codec spec must be a string, got {spec!r}")
    toks = spec.split(":")
    kind = toks[0]
    if kind in ("int8", "int4"):
        if len(toks) > 2:
            raise CodecSpecError(f"codec spec {spec!r}: expected "
                                 f"{kind}[:tile]")
        tile = _parse_tile(toks[1], spec) if len(toks) == 2 \
            else DEFAULT_TILE
        return CodecSpec(kind=kind, bits=4 if kind == "int4" else 8,
                         tile=tile)
    if kind == "topk":
        if len(toks) != 2:
            raise CodecSpecError(
                f"codec spec {spec!r}: topk needs a kept fraction, "
                "e.g. topk:0.05")
        try:
            frac = float(toks[1])
        except ValueError:
            raise CodecSpecError(
                f"codec spec {spec!r}: fraction must be a float, "
                f"got {toks[1]!r}") from None
        if not 0.0 < frac <= 1.0:
            raise CodecSpecError(
                f"codec spec {spec!r}: fraction must be in (0, 1]")
        return CodecSpec(kind="topk", frac=frac)
    if kind == "delta":
        if len(toks) == 1:
            return CodecSpec(kind="delta", delta_dtype="bfloat16")
        inner = toks[1]
        if inner in ("bf16", "bfloat16"):
            if len(toks) > 2:
                raise CodecSpecError(f"codec spec {spec!r}: bf16 delta "
                                     "takes no tile")
            return CodecSpec(kind="delta", delta_dtype="bfloat16")
        if inner == "int8":
            tile = _parse_tile(toks[2], spec) if len(toks) == 3 \
                else DEFAULT_TILE
            if len(toks) > 3:
                raise CodecSpecError(f"codec spec {spec!r}: expected "
                                     "delta:int8[:tile]")
            return CodecSpec(kind="delta", delta_dtype="int8", tile=tile)
        raise CodecSpecError(
            f"codec spec {spec!r}: delta payload must be bf16 or "
            f"int8, got {inner!r}")
    raise CodecSpecError(
        f"unknown codec kind {kind!r} in spec {spec!r}; known: "
        "int8, int4, topk, delta")


def parse_codec_map(codec) -> dict[str, CodecSpec]:
    """Validate a ``transport.codec`` mapping; returns
    {family: CodecSpec}.  Raises :class:`CodecSpecError` on an unknown
    family, a malformed spec, or a spec outside its family."""
    if codec is None:
        return {}
    if not isinstance(codec, dict):
        raise CodecSpecError(
            f"transport.codec must be a mapping of queue family to "
            f"codec spec, got {type(codec).__name__}")
    out: dict[str, CodecSpec] = {}
    for family, spec in codec.items():
        if family not in CODEC_FAMILIES:
            raise CodecSpecError(
                f"unknown codec family {family!r}; known: "
                f"{'/'.join(CODEC_FAMILIES)}")
        if spec in (None, "", "none"):
            continue
        parsed = parse_spec(spec)
        if parsed.kind not in _FAMILY_KINDS[family]:
            raise CodecSpecError(
                f"codec {parsed.kind!r} is not valid for the "
                f"{family!r} family (allowed: "
                f"{'/'.join(_FAMILY_KINDS[family])})")
        if family == "partial" and parsed.kind == "delta" \
                and parsed.delta_dtype != "int8":
            # the partial delta path is int8-only (the bf16 delta has
            # no tiled quantizer); rejecting here keeps the runtime
            # failure mode — an aggregator dying at flush AFTER
            # consuming its members — out of reach of a legal config
            raise CodecSpecError(
                f"codec spec {spec!r}: the partial family's delta "
                "form must be delta:int8[:tile]")
        out[family] = parsed
    return out
