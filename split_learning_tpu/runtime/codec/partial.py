"""Codec'd partial sums — the aggregator tree's wire codec
(``transport.codec: {partial: "int8:64"}`` / ``"delta:int8:64"``).

A :class:`~split_learning_tpu.runtime.protocol.PartialAggregate`
carries one group's per-stage weighted f32 SUMS — at fleet scale the
root's ingress is ``top_groups x stage_tree`` of raw fp32, the same
bandwidth problem PR 6 attacked on the activation plane.  This codec
compresses that leg the same way, host-side (aggregators never touch a
device, so the :mod:`~split_learning_tpu.runtime.codec.quant` numpy
twins apply):

* the sender ships the group **mean** (``sums / weight``) instead of
  the sums — bounded, parameter-scaled magnitudes that tile-quantize
  well, where raw sums grow with the fold weight;
* ``delta`` mode first subtracts the generation's START shard (the
  base the server distributed in :class:`~split_learning_tpu.runtime
  .protocol.AggAssign` and itself holds) — after one round of SGD the
  group mean sits a small step from the base, so the int8 tiles spend
  their range on the *training delta*;
* the mean (or delta) is tiled-absmax quantized
  (:func:`~split_learning_tpu.runtime.codec.quant.quantize_np`), and
  the receiver reconstructs ``sums = (base? + dequant) * weight`` in
  f32 before folding.

Semantics preserved at every level:

* **NaN propagation** — a non-finite tile ships a NaN scale
  (counted ``quant_nonfinite``), dequantizes to NaN, and hits the fold
  backend's ingest exactly like a NaN in a raw f32 partial would;
* **dedup** — the codec is payload-only: group keys, member metadata
  and the fold-level dup drops are untouched;
* **self-description** — the frame's ``codec``/``codec_base`` fields
  say how to decode, so a raw-f32 partial (codec off, the bit-parity
  leg) and a codec'd one can share every consumer.  A delta partial
  whose base the receiver does not hold is dropped and counted
  (``partial_codec_errors``) — never mis-reconstructed.

Batch-stat sums quantize WITHOUT the delta (running statistics drift
away from the START base too fast for the delta to help, and plumbing
a second base tree is not worth the bytes — they are a tiny fraction
of the frame).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from split_learning_tpu.runtime.codec.quant import (
    dequantize_leaf_np, quantize_np,
)
from split_learning_tpu.runtime.codec.specs import CodecSpec, parse_spec
from split_learning_tpu.runtime.protocol import QuantLeaf


class PartialCodecError(ValueError):
    """A codec'd partial could not be reconstructed (unknown spec,
    missing/mismatched delta base)."""


def _flat_items(tree):
    from split_learning_tpu.ops.fedavg import walk_items
    return walk_items(tree)


def _unflatten(flat):
    from split_learning_tpu.ops.fedavg import unflatten_items
    return unflatten_items(flat)


def _resolve_spec(spec: CodecSpec | str) -> CodecSpec:
    return parse_spec(spec) if isinstance(spec, str) else spec


def spec_string(spec: CodecSpec) -> str:
    """The self-describing wire form of a partial codec spec."""
    if spec.kind == "delta":
        return f"delta:{'int8' if spec.delta_dtype == 'int8' else 'bf16'}" \
            + (f":{spec.tile}" if spec.delta_dtype == "int8" else "")
    return f"{spec.kind}:{spec.tile}"


def _quant_bits(spec: CodecSpec) -> int:
    if spec.kind == "delta":
        # delta:bf16 has no integer quantizer; callers guard on it
        return 8
    return spec.bits


def encode_partial_entry(ent: dict, spec: CodecSpec | str,
                         base: Any = None, base_gen: int | None = None,
                         faults=None) -> tuple[dict, str, int | None]:
    """Compress one ``StreamingFold.partial()`` stage entry in place
    (a copy — the caller's entry is untouched).

    Returns ``(entry, codec_string, codec_base)`` for the
    PartialAggregate fields.  ``base`` is the stage's START shard tree
    (delta mode); paths absent from it quantize plain."""
    spec = _resolve_spec(spec)
    delta = spec.kind == "delta"
    if delta and spec.delta_dtype != "int8":
        raise PartialCodecError(
            "partial delta codec supports int8 payloads only "
            f"(got {spec.delta_dtype!r})")
    out = dict(ent)
    base_flat = dict(_flat_items(base)) if (delta and base is not None) \
        else {}
    used_base = False
    for sums_key, w_key in (("sums", "weight"),
                            ("stat_sums", "stat_weight")):
        sums = ent.get(sums_key)
        w = float(ent.get(w_key) or 0.0)
        if not sums or w == 0.0:
            continue
        flat: dict = {}
        for path, leaf in _flat_items(sums):
            a = np.asarray(leaf, np.float32)
            mean = a / np.float32(w)
            b = base_flat.get(path) if sums_key == "sums" else None
            if b is not None and np.shape(b) == mean.shape:
                mean = mean - np.asarray(b, np.float32)
                used_base = True
            q = quantize_np(mean, spec.tile, bits=_quant_bits(spec))
            if not np.isfinite(np.asarray(q.scale)).all():
                if faults is not None:
                    faults.inc("quant_nonfinite")
            flat[path] = q
        out[sums_key] = _unflatten(flat)
    return (out, spec_string(spec),
            base_gen if (delta and used_base) else None)


def decode_partial_entry(ent: dict, codec: str,
                         codec_base: int | None = None,
                         base: Any = None,
                         base_gen: int | None = None) -> dict:
    """Reconstruct f32 sums from a codec'd stage entry; raises
    :class:`PartialCodecError` when the delta base is required but
    missing or from a different generation — the caller counts
    ``partial_codec_errors`` and drops the frame (a mis-reconstructed
    fold would be silently wrong, the one outcome worse than a lost
    partial)."""
    spec = _resolve_spec(codec)
    if codec_base is not None:
        if base is None or base_gen != codec_base:
            raise PartialCodecError(
                f"delta partial against base gen {codec_base} but the "
                f"receiver holds "
                f"{'none' if base is None else f'gen {base_gen}'}")
    base_flat = dict(_flat_items(base)) if (codec_base is not None
                                            and base is not None) else {}
    out = dict(ent)
    for sums_key, w_key in (("sums", "weight"),
                            ("stat_sums", "stat_weight")):
        sums = ent.get(sums_key)
        w = float(ent.get(w_key) or 0.0)
        if not sums:
            continue
        flat: dict = {}
        for path, leaf in _flat_items(sums):
            if isinstance(leaf, QuantLeaf):
                mean = dequantize_leaf_np(leaf)
                b = base_flat.get(path) if sums_key == "sums" else None
                if b is not None:
                    if np.shape(b) != mean.shape:
                        raise PartialCodecError(
                            f"delta base shape {np.shape(b)} != "
                            f"partial {mean.shape} at {path!r}")
                    mean = mean + np.asarray(b, np.float32)
                flat[path] = (mean * np.float32(w)).astype(np.float32)
            else:
                flat[path] = np.asarray(leaf, np.float32)
        out[sums_key] = _unflatten(flat)
    return out


def msg_entry(msg) -> dict:
    """The stage-entry view of a PartialAggregate's payload fields —
    the shape both codec halves operate on."""
    return {"sums": msg.sums, "weight": msg.weight,
            "stat_sums": msg.stat_sums, "stat_weight": msg.stat_weight}


def decode_partial_msg(msg, bases: dict | None = None,
                       base_gen: int | None = None) -> None:
    """Decode a PartialAggregate IN PLACE when it carries a codec
    (no-op on raw f32 frames).  ``bases`` maps stage -> START shard
    tree for the delta mode.  Packed member metadata
    (``members_z``) is restored to the plain list first — it is the
    other O(clients) term the codec compresses."""
    if getattr(msg, "members_z", None):
        from split_learning_tpu.runtime.protocol import unpack_members
        msg.members = unpack_members(msg.members_z)
        msg.members_z = None
    if not msg.codec:
        return
    base = (bases or {}).get(msg.stage)
    ent = decode_partial_entry(
        msg_entry(msg), msg.codec, codec_base=msg.codec_base,
        base=base, base_gen=base_gen)
    msg.sums = ent["sums"]
    msg.stat_sums = ent["stat_sums"]
    msg.codec = None
    msg.codec_base = None
