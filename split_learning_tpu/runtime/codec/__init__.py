"""Composable wire codec layer between the runtime and the SLT2 frame
format (ROADMAP open item 3; *Ampere*, arxiv 2507.07130).

A codec is a per-queue-family policy configured in ``transport.codec``
(:mod:`~split_learning_tpu.runtime.codec.specs` owns the grammar):

* ``intermediate`` — :class:`~.quant.QuantCodec`: tiled absmax
  int8/int4 activation quantization, scales computed ON DEVICE before
  the fetch;
* ``gradient`` — :class:`~.quant.QuantCodec` or
  :class:`~.sparse.TopKCodec`: top-k sparsification with a seeded,
  checkpointable error-feedback residual;
* ``rpc`` — :class:`~.delta.DeltaCodec`: Update frames carry
  ``params - last_server_acked`` against the server's versioned shadow
  copies, with automatic full-frame resync when the version chain
  breaks.

Every codec composes under the Reliable/Chaos/Async transports: it
transforms the PAYLOAD tree before ``encode_parts`` and after decode,
so envelopes, chunking, checksums and the wire trace context are
untouched.  The shared shape is a two-phase encoder matching the async
data plane: ``prepare(tree, key)`` runs on the training thread (device
ops + any stateful residual update, so state order == publish order)
and ``encode(prepared)`` runs on the async sender thread (host fetch +
wire-leaf construction).

This module stays import-light (``specs`` only); the codec classes
pull in jax and are imported lazily by :func:`make_codecs`.
"""

from __future__ import annotations

import numpy as np

from split_learning_tpu.runtime.codec.specs import (  # noqa: F401
    CODEC_COUNTERS, CODEC_FAMILIES, CodecSpec, CodecSpecError,
    parse_codec_map, parse_spec,
)

__all__ = [
    "CODEC_COUNTERS", "CODEC_FAMILIES", "CodecSpec", "CodecSpecError",
    "parse_codec_map", "parse_spec", "make_codecs", "wire_raw_nbytes",
]


def make_codecs(cfg, faults=None) -> dict:
    """{family: codec instance} for one participant, from
    ``cfg.transport.codec``.  Families without a spec are absent —
    callers fall back to the plain wire-dtype path."""
    specs = parse_codec_map(getattr(cfg.transport, "codec", None))
    # a full config carries the Pallas kernel plan for this process —
    # install it so the self-describing decode path (no config in
    # scope) follows the same plan; partial shims (no `kernels`
    # section) leave the installed plan alone
    kcfg = getattr(cfg, "kernels", None)
    if kcfg is not None:
        from split_learning_tpu.ops import kernels as kplane
        kplane.configure(kcfg)
    out: dict = {}
    for family, spec in specs.items():
        if spec.kind in ("int8", "int4"):
            from split_learning_tpu.runtime.codec.quant import QuantCodec
            out[family] = QuantCodec(spec, faults=faults, kernels=kcfg)
        elif spec.kind == "topk":
            from split_learning_tpu.runtime.codec.sparse import TopKCodec
            out[family] = TopKCodec(spec, faults=faults)
        elif spec.kind == "delta":
            from split_learning_tpu.runtime.codec.delta import DeltaCodec
            out[family] = DeltaCodec(spec, faults=faults)
    return out


def wire_raw_nbytes(tree, wire_dtype) -> int:
    """Bytes this payload tree WOULD occupy on the plain (codec-less)
    wire: float leaves at the configured wire dtype, everything else at
    its own width.  Shape-only — no device sync.  Feeds the
    ``raw_bytes_out`` wire counter, the honest denominator of
    ``extra.wire_compression_ratio``."""
    import jax
    import jax.numpy as jnp

    itemsize = np.dtype(wire_dtype).itemsize
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        ldt = getattr(leaf, "dtype", None)
        if ldt is None:
            continue
        n = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
        if ldt == jax.dtypes.float0:
            total += n * 4
        elif jnp.issubdtype(ldt, jnp.floating):   # incl. bfloat16
            total += n * itemsize
        else:
            total += n * np.dtype(ldt).itemsize
    return total
