"""Delta-encoded Update frames — the rpc wire codec
(``transport.codec: {rpc: "delta:int8"}``).

A round's UPDATE is the biggest frame a client publishes (the full
trained shard, fp32).  But the server already SENT this client a shard
in START — after one round of SGD the trained params sit a small step
away from that base, so the client ships ``trained - base`` instead,
quantized (bf16 or tiled int8) with a client-side error-feedback
residual, tagged with the base's **version** (the server's per-
invocation generation).

Both endpoints keep the base: the client remembers the params exactly
as received in START, the server keeps a **versioned shadow copy per
client** (:class:`DeltaShadow`, recorded at START fan-out) and folds
``base + dequant(delta)`` back into a full tree before aggregation
(``runtime/strategies.py`` only ever sees reconstructed updates).

The version chain self-heals: the server advertises the shadow version
it holds in every START (``extra["delta_base_version"]``), and a
client sends a delta ONLY when its local base matches that
advertisement — a restarted client (no base), a hold-weights round
whose base drifted, or a server that lost its shadow all degrade to a
full fp32 frame automatically (counted ``delta_full_frames``).  A
delta that still arrives against a version the shadow lacks
(redelivery gap, shadow loss after fan-out) is rejected and counted
``delta_resyncs``; the server marks the client for a full re-seed so
the next round repairs the chain.

This path runs once per round on host-side trees, so the quantizer is
the numpy twin in :mod:`~split_learning_tpu.runtime.codec.quant` — the
device-side discipline the slcheck codec analyzer enforces applies to
the per-microbatch data plane, not here.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from split_learning_tpu.runtime.codec.quant import (
    dequantize_leaf_np, quantize_np,
)
from split_learning_tpu.runtime.codec.specs import CodecSpec
from split_learning_tpu.runtime.protocol import QuantLeaf

try:
    import ml_dtypes as _ml_dtypes
    _BF16 = np.dtype(_ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - jax ships it
    _BF16 = None


def _tree_map_np(fn, *trees):
    import jax
    return jax.tree_util.tree_map(fn, *trees)


class DeltaCodec:
    """Client half: encode ``trained - base`` (+ EF residual)."""

    name = "delta"
    COUNTERS = ("delta_folds", "delta_full_frames", "delta_resyncs",
                "quant_nonfinite")

    def __init__(self, spec: CodecSpec, faults=None):
        self.delta_dtype = spec.delta_dtype
        self.tile = spec.tile
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        # leaf-index -> residual (what quantization dropped last round)
        self._res: dict[int, np.ndarray] = {}

    def encode_update(self, params: Any, base: Any) -> Any:
        """Full trained tree + base tree (both host np, matching
        structure) -> quantized delta tree."""
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(params)
        base_leaves = jax.tree_util.tree_leaves(base)
        if len(leaves) != len(base_leaves):
            raise ValueError("delta base/params structure mismatch")
        out = []
        for i, (p, b) in enumerate(zip(leaves, base_leaves)):
            p = np.asarray(p)
            if not np.issubdtype(p.dtype, np.floating):
                out.append(p)
                continue
            res = self._res.get(i)
            if res is not None and np.shape(res) != p.shape:
                # an elastic re-plan moved this client's layer range:
                # leaf i is a different tensor now — the old residual
                # is another shard's unsent mass, drop it
                res = None
            d = (p.astype(np.float32) - np.asarray(b, np.float32)
                 + (res if res is not None else np.float32(0.0)))
            if self.delta_dtype == "int8":
                leaf = quantize_np(d, self.tile, bits=8)
                if not np.isfinite(np.asarray(leaf.scale)).all():
                    self.faults.inc("quant_nonfinite")
                sent = dequantize_leaf_np(leaf)
            else:
                if _BF16 is None:  # pragma: no cover - jax ships it
                    leaf = d
                    sent = d
                else:
                    leaf = d.astype(_BF16)
                    sent = np.asarray(leaf, np.float32)
            self._res[i] = d - sent
            out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- checkpointable residual state ---------------------------------

    def state_dict(self) -> dict[str, np.ndarray]:
        return {f"delta|{i}": np.asarray(r)
                for i, r in sorted(self._res.items())}

    def load_state_dict(self, state: dict) -> None:
        self._res = {}
        for name, arr in state.items():
            _, _, i = name.rpartition("|")
            self._res[int(i)] = np.asarray(arr, np.float32)


def decode_delta_tree(delta: Any) -> Any:
    """Quantized delta tree -> float32 np delta tree (server side).
    Non-float leaves passed through the encoder unchanged stay as-is
    (they carry the full trained value, not a delta)."""
    def conv(leaf):
        if isinstance(leaf, QuantLeaf):
            return dequantize_leaf_np(leaf)
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            return a.astype(np.float32)
        return a
    return _tree_map_np(conv, delta)


class DeltaShadow:
    """Server half: versioned per-client shadow copies + the fold.

    ``note_sent`` records the exact tree a START carried (keyed by the
    invocation generation); ``fold`` reconstructs a delta UPDATE
    against it.  One version per client is enough — the client can
    only ever hold the latest base (a delta against an older one means
    the chain broke, which is exactly what fold refuses)."""

    def __init__(self, faults=None):
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        self._shadow: dict[str, tuple[int, Any]] = {}
        # running byte total, maintained incrementally: nbytes() sits
        # on the server's round path (gauge refresh per START fan-out
        # and per lost-client prune), so an O(clients x leaves) rescan
        # there would scale with exactly the fleet width the streaming
        # aggregation plane exists to remove
        self._nbytes_total = 0
        self._nbytes_by_client: dict[str, int] = {}
        # the lost-client prune runs on whatever thread advances the
        # FleetMonitor — including the exporter's HTTP handler — while
        # note_sent/fold run on the round/pump thread; the compound
        # ledger updates (total += new - old) need the lock or a
        # clear/note_sent interleave drifts the gauge and can pin a
        # pruned client's tree uncounted
        self._lock = threading.Lock()

    @staticmethod
    def _tree_nbytes(tree: Any) -> int:
        import jax
        return sum(int(np.asarray(leaf).nbytes)
                   for leaf in jax.tree_util.tree_leaves(tree))

    def note_sent(self, client_id: str, version: int, tree: Any) -> None:
        n = self._tree_nbytes(tree)
        with self._lock:
            self._nbytes_total += (n
                                   - self._nbytes_by_client.get(
                                       client_id, 0))
            self._nbytes_by_client[client_id] = n
            self._shadow[client_id] = (version, tree)

    def version_for(self, client_id: str) -> int | None:
        with self._lock:
            ent = self._shadow.get(client_id)
        return ent[0] if ent is not None else None

    def clear(self, client_id: str | None = None) -> None:
        with self._lock:
            if client_id is None:
                self._shadow.clear()
                self._nbytes_total = 0
                self._nbytes_by_client.clear()
            else:
                self._shadow.pop(client_id, None)
                self._nbytes_total -= self._nbytes_by_client.pop(
                    client_id, 0)

    def nbytes(self) -> int:
        """Host bytes pinned across every client's shadow tree — the
        ``sl_agg_shadow_bytes`` gauge (memory audit: without the
        lost-client and elastic prunes this grows without bound under
        membership churn).  O(1): maintained incrementally by
        note_sent/clear."""
        with self._lock:
            return self._nbytes_total

    def fold(self, client_id: str, base_version: int,
             delta: Any) -> Any | None:
        """base + dequant(delta) as a full float tree, or None when the
        shadow does not hold ``base_version`` for this client (version
        gap -> the caller must trigger a full-frame resync)."""
        with self._lock:
            ent = self._shadow.get(client_id)
        if ent is None or ent[0] != base_version:
            self.faults.inc("delta_resyncs")
            return None
        _, base = ent
        self.faults.inc("delta_folds")
        d32 = decode_delta_tree(delta)

        def comb(b, d):
            b = np.asarray(b)
            if np.issubdtype(b.dtype, np.floating):
                # float leaves fold base + delta, back in the master
                # dtype (fp32 — the master path stays full precision)
                return (b.astype(np.float32)
                        + np.asarray(d, np.float32)).astype(b.dtype)
            return np.asarray(d)   # non-float leaves ship whole
        return _tree_map_np(comb, base, d32)
