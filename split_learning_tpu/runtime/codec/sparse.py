"""Top-k gradient sparsification with error feedback — the gradient
wire codec (``transport.codec: {gradient: "topk:0.05"}``).

Each published gradient keeps only the k largest-magnitude entries of
(gradient + residual); everything not sent accumulates in a
client-side **error-feedback residual** and rides the NEXT publish to
the same destination, so the training signal is delayed, never lost
(the standard EF-SGD construction; *Ampere*, arxiv 2507.07130, applies
the same idea at the split-learning cut).

Determinism is a hard contract here (the chaos soaks prove compressed
rounds aggregate bit-identical under drop/dup/reorder):

* the residual state is initialized to zeros and advanced ON THE
  TRAINING THREAD at prepare time, in publish order — channel faults
  happen below, so the published stream is a pure function of the
  training stream;
* selection runs on device via ``jax.lax.top_k`` inside a jitted
  kernel (fixed tie policy), and the chosen indices are sorted so the
  wire bytes are order-canonical;
* the state is keyed by (destination queue, leaf index): the SDA
  head's per-origin gradient returns each get their own residual, so
  window composition cannot cross the streams.

The residual is **checkpointable** (``state_dict``/``load_state_dict``
+ the atomic sidecar in ``runtime/checkpoint.py``): a restarted client
resumes with its unsent mass instead of silently dropping it.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.runtime.codec.specs import CodecSpec
from split_learning_tpu.runtime.protocol import SparseLeaf

#: leaves smaller than this ship dense (index+value overhead would
#: exceed the dense bytes)
MIN_SPARSE_SIZE = 64


class DevTopK:
    """Device-staged sparse leaf (idx/val still on device); the async
    sender's encode thunk turns it into a wire :class:`SparseLeaf`."""

    def __init__(self, idx: Any, val: Any, shape: tuple):
        self.idx = idx
        self.val = val
        self.shape = tuple(int(s) for s in shape)


jax.tree_util.register_pytree_node(
    DevTopK,
    lambda d: ((d.idx, d.val), (d.shape,)),
    lambda aux, ch: DevTopK(ch[0], ch[1], *aux))


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_dev(g, res, k: int):
    """(sorted idx, values, new residual) for one flat f32 gradient."""
    acc = g.reshape(-1).astype(jnp.float32) + res
    _, idx = jax.lax.top_k(jnp.abs(acc), k)
    idx = jnp.sort(idx)            # canonical wire order
    val = acc[idx]
    new_res = acc.at[idx].set(0.0)
    return idx.astype(jnp.int32), val, new_res


class TopKCodec:
    """Stateful per-client top-k + error-feedback gradient codec."""

    name = "topk"
    COUNTERS = ("topk_dense_fallbacks",)

    def __init__(self, spec: CodecSpec, faults=None):
        self.frac = spec.frac
        if faults is None:
            from split_learning_tpu.runtime.trace import (
                default_fault_counters,
            )
            faults = default_fault_counters
        self.faults = faults
        # (destination queue, leaf index) -> flat f32 device residual
        self._res: dict[tuple[str, int], Any] = {}

    def _k(self, n: int) -> int:
        return max(1, math.ceil(self.frac * n))

    def prepare(self, tree, key: str = ""):
        """Device-side stage (training thread — residual order IS
        publish order).  ``key`` is the destination queue."""
        leaves, treedef = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda o: isinstance(o, DevTopK))
        out = []
        for i, leaf in enumerate(leaves):
            ldt = getattr(leaf, "dtype", None)
            n = int(np.prod(np.shape(leaf))) if np.shape(leaf) else 1
            if (ldt is None or ldt == jax.dtypes.float0
                    or not jnp.issubdtype(ldt, jnp.floating)
                    or n < MIN_SPARSE_SIZE or self._k(n) >= n):
                if (ldt is not None and ldt != jax.dtypes.float0
                        and jnp.issubdtype(ldt, jnp.floating)):
                    self.faults.inc("topk_dense_fallbacks")
                out.append(leaf)
                continue
            skey = (key, i)
            res = self._res.get(skey)
            if res is None or res.shape[0] != n:
                # fresh stream, OR an elastic re-plan changed this
                # leaf's layout (moved cuts => different boundary
                # shape): a stale residual is a different tensor's
                # unsent mass — reset rather than crash or corrupt
                res = jnp.zeros((n,), jnp.float32)
            x = jnp.asarray(leaf)
            idx, val, new_res = _topk_dev(x, res, self._k(n))
            self._res[skey] = new_res
            out.append(DevTopK(idx, val, x.shape))
        return jax.tree_util.tree_unflatten(treedef, out)

    def encode(self, prepared):
        """Host-side stage: fetch idx/val, build wire SparseLeaf."""
        def conv(leaf):
            if isinstance(leaf, DevTopK):
                return SparseLeaf(idx=np.asarray(leaf.idx, np.int32),
                                  val=np.asarray(leaf.val, np.float32),
                                  shape=leaf.shape)
            if getattr(leaf, "dtype", None) == jax.dtypes.float0:
                return np.zeros(np.shape(leaf), np.float32)
            return np.asarray(leaf)
        return jax.tree_util.tree_map(
            conv, prepared, is_leaf=lambda o: isinstance(o, DevTopK))

    # -- checkpointable residual state ---------------------------------

    def state_dict(self) -> dict[str, np.ndarray]:
        """Flat {"<queue>|<leaf-idx>": residual} snapshot (host np)."""
        return {f"{q}|{i}": np.asarray(r)
                for (q, i), r in sorted(self._res.items())}

    def load_state_dict(self, state: dict) -> None:
        self._res = {}
        for name, arr in state.items():
            q, _, i = name.rpartition("|")
            self._res[(q, int(i))] = jnp.asarray(arr, jnp.float32)


def densify_leaf(leaf: SparseLeaf):
    """Wire SparseLeaf -> dense device float32 (receiver hot path)."""
    n = int(np.prod(leaf.shape)) if leaf.shape else 1
    idx = np.asarray(leaf.idx)
    if idx.size and (idx.min() < 0 or idx.max() >= n):
        # decoded straight off the wire: a crafted/corrupt index must
        # fail loudly, not scatter out of bounds (jit clamps silently)
        from split_learning_tpu.runtime.protocol import CorruptFrame
        raise CorruptFrame(
            f"sparse leaf index out of range for shape {leaf.shape}")
    dense = jnp.zeros((n,), jnp.float32).at[jnp.asarray(idx)].set(
        jnp.asarray(leaf.val, jnp.float32))
    return dense.reshape(leaf.shape)
