"""Closed-loop resource-aware scheduler: telemetry in, plan out.

The reference's third pillar (``src/Cluster.py`` KMeans client
clustering, ``src/Selection.py`` GMM straggler rejection,
``src/Partition.py`` throughput-optimal cut selection) ran ONCE, at the
registration barrier, on self-reported profiles.  Everything it decided
was frozen for the life of the run — a client that slowed down after
round 3 set the round wall forever.  This module is the live
counterpart: a decision loop running at round boundaries on the
protocol server, consuming the planes the last five PRs built
(per-client EWMA rate and compute rate, step p95, version lag,
compute-slow vs wire-slow attribution — ``runtime/telemetry.py`` +
``runtime/perf.py``) and closing the loop back into the plan:

* **online clustering** (:class:`OnlineClusterer`) — mini-batch KMeans
  over each client's label-distribution sketch + measured compute/wire
  ratio, with sticky re-assignment hysteresis so membership churn
  cannot flap assignments;
* **straggler policy** — per-boundary scoring with attribution; a
  straggler is first DEMOTED with per-client knob retunes (heavier
  wire codec for wire-slow, wider staleness window + quorum exemption
  for compute-slow — the PR 6/10 knobs, driven per client instead of
  one global config) and EVICTED through the elastic-drop path after
  ``scheduler.evict-after`` consecutive straggler boundaries;
* **cut re-planning** — the measured-throughput model in
  :mod:`split_learning_tpu.planner.throughput` re-runs the max-min
  pipeline-balance search on live rates each boundary; a new cut ships
  through the existing re-plan/START machinery only when it beats the
  incumbent's predicted round wall by ``scheduler.replan-damping``
  (anti-flap) and the cooldown has elapsed;
* **mid-round barrier drops** — a NOTIFY/UPDATE barrier may stop
  waiting for a health-state-straggler client after
  ``scheduler.barrier-grace-s`` seconds (the same early-release shape
  as the fleet-liveness drop, but policy-driven).

Every decision flows through :meth:`Scheduler.journal` and lands as a
``kind=sched`` metrics record — the slcheck ``sched`` analyzer (SC001)
statically enforces that every ``_act_*`` decision site journals, so
no control action is ever silent.  Decisions are DETERMINISTIC given
the same telemetry snapshots and seed: all iteration is over sorted
client ids, all randomness is drawn from ``(scheduler.seed, round)``.

No jax, no protocol imports: plan surgery happens on
:class:`~split_learning_tpu.runtime.plan.ClusterPlan` dataclasses, the
server owns every wire side effect (STOP fan-out, shadow reclaim,
``_needs_params`` marking).
"""

from __future__ import annotations

import collections
import dataclasses
import statistics
import threading
import time
from typing import Any, Sequence

import numpy as np

from split_learning_tpu.runtime import blackbox
from split_learning_tpu.runtime.plan import (
    ClusterPlan, prune_plan_members,
)

#: journal actions the validator admits (``validate_journal``)
ACTIONS = ("decide", "evict", "evict-skip", "demote", "promote",
           "replan", "drop", "cluster", "retune")

#: aggregation.fan-in candidates the retune search scans (ROADMAP
#: item 1, 1M tier): small enough to keep per-node fold walls bounded,
#: large enough to keep the tree shallow on big fleets
FANIN_CANDIDATES = (2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)

#: score threshold mirroring FleetMonitor.STRAGGLER_SCORE: a rate (or
#: compute rate) below this fraction of the fleet median is slow
SLOW_SCORE = 0.5


@dataclasses.dataclass
class SchedOutcome:
    """One boundary's decisions, for the server to apply."""
    round_idx: int
    evict: set                       # client ids to evict (elastic path)
    plans: list | None               # replacement plans, or None
    fan_in: int | None = None        # retuned aggregation.fan-in
    decision_ms: float = 0.0


class OnlineClusterer:
    """Mini-batch KMeans with sticky re-assignment hysteresis.

    ``update`` takes the current feature map (sorted-client iteration,
    deterministic), partial-fits at most ``minibatch`` points into the
    running centroids (so the per-boundary cost is bounded however
    large the fleet grows), then re-assigns: a client keeps its
    current cluster unless another centroid is at least ``hysteresis``
    fractionally closer — the damping that keeps assignments stable
    while clients join, leave and drift."""

    def __init__(self, k: int, hysteresis: float = 0.25,
                 minibatch: int = 1024, seed: int = 0):
        self.k = max(1, int(k))
        self.hysteresis = float(hysteresis)
        self.minibatch = int(minibatch)
        self.seed = int(seed)
        self.centers: np.ndarray | None = None
        self._counts: np.ndarray | None = None
        self.assignment: dict[str, int] = {}

    def _init_centers(self, x: np.ndarray,
                      rng: np.random.Generator) -> None:
        k = min(self.k, x.shape[0])
        centers = np.empty((k, x.shape[1]))
        centers[0] = x[rng.integers(x.shape[0])]
        d2 = ((x - centers[0]) ** 2).sum(axis=1)
        for i in range(1, k):
            total = d2.sum()
            if total <= 0:
                centers[i] = x[rng.integers(x.shape[0])]
            else:
                centers[i] = x[rng.choice(x.shape[0], p=d2 / total)]
            d2 = np.minimum(d2, ((x - centers[i]) ** 2).sum(axis=1))
        self.centers = centers
        self._counts = np.ones(k)

    def update(self, features: dict[str, Sequence[float]],
               round_idx: int) -> tuple[dict[str, int], list[str]]:
        """Fit + assign.  Returns ``(assignment, moved_client_ids)``."""
        cids = sorted(features)
        if not cids:
            return dict(self.assignment), []
        x = np.asarray([features[c] for c in cids], dtype=float)
        rng = np.random.default_rng((self.seed, round_idx))
        if self.centers is None or self.centers.shape[1] != x.shape[1]:
            self._init_centers(x, rng)
        assert self.centers is not None and self._counts is not None
        # mini-batch partial fit (Sculley 2010): each sampled point
        # pulls its nearest centroid with a 1/count learning rate
        batch = (np.arange(len(cids))
                 if len(cids) <= self.minibatch
                 else rng.choice(len(cids), size=self.minibatch,
                                 replace=False))
        for i in np.sort(batch):
            d2 = ((self.centers - x[i]) ** 2).sum(axis=1)
            j = int(d2.argmin())
            self._counts[j] += 1
            lr = 1.0 / self._counts[j]
            self.centers[j] = (1 - lr) * self.centers[j] + lr * x[i]
        # vectorized assignment, sticky: keep the current cluster
        # unless a rival centroid is a full hysteresis margin closer
        d2 = ((x[:, None, :] - self.centers[None, :, :]) ** 2).sum(2)
        nearest = d2.argmin(axis=1)
        moved: list[str] = []
        out: dict[str, int] = {}
        for i, cid in enumerate(cids):
            cur = self.assignment.get(cid)
            if cur is None or cur >= self.centers.shape[0]:
                out[cid] = int(nearest[i])
                if cur is not None:
                    moved.append(cid)
                continue
            if (nearest[i] != cur
                    and d2[i, nearest[i]]
                    < (1.0 - self.hysteresis) * d2[i, cur]):
                out[cid] = int(nearest[i])
                moved.append(cid)
            else:
                out[cid] = cur
        # forget departed clients so churn cannot grow the map forever
        self.assignment = out
        return dict(out), moved


def validate_journal(records: Sequence[dict]) -> list[str]:
    """Schema check over a run's ``kind=sched`` records: every control
    action must be fully attributable — action name from the known
    vocabulary, the round it was taken at, a human-readable why, and a
    client/cluster subject where the action has one.  Returns a list
    of violations (empty = valid); used by the chaos ``--sched`` cell
    and the determinism tests."""
    errs: list[str] = []
    for i, rec in enumerate(records):
        act = rec.get("action")
        if act not in ACTIONS:
            errs.append(f"record {i}: unknown action {act!r}")
            continue
        if not isinstance(rec.get("round"), int):
            errs.append(f"record {i} ({act}): missing integer round")
        if act != "decide" and not rec.get("why"):
            errs.append(f"record {i} ({act}): missing why")
        if act in ("evict", "demote", "promote", "drop", "cluster") \
                and not rec.get("client"):
            errs.append(f"record {i} ({act}): missing client")
        if act == "replan":
            det = rec.get("detail") or {}
            if "cuts_to" not in det or "cuts_from" not in det:
                errs.append(f"record {i} (replan): missing cuts detail")
        if act == "retune":
            det = rec.get("detail") or {}
            if "fan_in_to" not in det or "fan_in_from" not in det:
                errs.append(f"record {i} (retune): missing fan-in "
                            "detail")
    return errs


class Scheduler:
    """The round-boundary decision loop (one per ProtocolContext).

    The server calls :meth:`plan_round` between rounds with the
    current plans, the FleetMonitor's ``/fleet`` snapshot and the
    registration profiles; the returned :class:`SchedOutcome` names
    evictions and (possibly) replacement plans.  During a round the
    barriers consult :meth:`barrier_drop`, the START fan-out ships
    :meth:`knobs_for` per client, and the async admission window reads
    :meth:`staleness_bonus_for` / :meth:`quorum_exempt`."""

    #: bounded decision journal (the /fleet + topology() view)
    MAX_JOURNAL = 1024
    #: members fed to the cut-search cost model per cluster (evenly
    #: strided over the sorted membership; see _replan_plan)
    REPLAN_MEMBER_SAMPLE = 64

    def __init__(self, cfg, log=None, faults=None, gauges=None):
        self.cfg = cfg
        self.sch = cfg.scheduler
        # guards the journal/last-action/replan views: topology() is
        # served from the telemetry exporter's HTTP threads while the
        # protocol thread journals decisions
        self._lock = threading.Lock()
        self.log = log
        self.faults = faults
        self.gauges = gauges
        self.clusterer = OnlineClusterer(
            k=self.sch.clusters or 1,
            hysteresis=self.sch.hysteresis,
            minibatch=self.sch.minibatch, seed=self.sch.seed)
        self.decisions: collections.deque = collections.deque(
            maxlen=self.MAX_JOURNAL)
        self.last_action: dict[str, str] = {}
        self.last_replan: dict | None = None
        self._ledger: dict[str, int] = {}   # consecutive straggler
        self._healthy: dict[str, int] = {}  # consecutive healthy
        # boundaries while demoted — the promote-side hysteresis
        self._knobs: dict[str, dict] = {}   # cid -> START extra.sched
        self._stale_bonus: dict[str, int] = {}
        self._exempt: set = set()
        self._evicted: set = set()
        self._last_replan_round: int | None = None
        self._last_decide_round: int | None = None
        # aggregator fan-in retuning (ROADMAP item 1, 1M tier): the
        # LIVE fan-in (the server mirrors adopted retunes into its
        # aggregation view) and the cooldown anchor, damped exactly
        # like cut re-planning
        self._fan_in = int(getattr(cfg.aggregation, "fan_in", 0))
        self._last_fanin_round: int | None = None
        self._stage_stats: dict = {}   # telemetry "stages" block
        # first boundary pass that was past warmup: until it has
        # happened, the mid-round barrier policy stays inert — round 0
        # must never drop a client on seconds-old telemetry
        self._last_acting_round: int | None = None

    # -- journal (the ONE exit for decisions; SC001) -------------------------

    def journal(self, action: str, round_idx: int, client=None,
                cluster=None, why: str = "", detail=None) -> None:
        """Record one decision: a ``kind=sched`` metrics record plus
        the bounded in-memory journal the ``/fleet`` view serves.
        Deterministic content only — wall-clock cost rides the
        ``decide`` summary's detail, never an action record."""
        rec = {"action": action, "round": int(round_idx),
               "client": client, "cluster": cluster, "why": why,
               "detail": detail or {}}
        with self._lock:
            self.decisions.append(rec)
            if client is not None:
                self.last_action[client] = f"{action}@r{round_idx}"
        # flight-recorder feed: control-plane actions belong on the
        # postmortem timeline next to the frames they caused
        if blackbox.enabled():
            blackbox.record("sched", action=action,
                            round=int(round_idx), client=client,
                            cluster=cluster, why=why or None)
        if self.log is not None:
            self.log.metric(kind="sched", **rec)
            if action not in ("decide",):
                who = client if client is not None \
                    else f"cluster {cluster}"
                self.log.info(f"sched: {action} {who} r{round_idx}"
                              + (f" ({why})" if why else ""), "cyan")

    # -- per-round inputs ----------------------------------------------------

    @staticmethod
    def _views(fleet: dict) -> dict[str, dict]:
        """Per-client telemetry views (training clients only — an
        aggregator node is never schedulable)."""
        out = {}
        for cid, c in (fleet.get("clients") or {}).items():
            if c.get("kind", "client") != "client":
                continue
            out[cid] = c
        return out

    def _features(self, plans: list, views: dict) -> dict:
        """Clustering features: L1-normalized label distribution (the
        reference Cluster.py input) + the measured compute/wire ratio
        (end-to-end rate over device rate; 1.0 = wire-free) as one
        extra dimension."""
        label_of: dict[str, np.ndarray] = {}
        n_classes = 1
        for p in plans:
            lc = np.asarray(p.label_counts, dtype=float)
            if lc.ndim == 2 and lc.shape[0] == len(p.stage1_clients):
                n_classes = max(n_classes, lc.shape[1])
                for i, cid in enumerate(p.stage1_clients):
                    row = lc[i]
                    norm = np.abs(row).sum() or 1.0
                    label_of[cid] = row / norm
        feats = {}
        for cid in sorted(label_of):
            v = views.get(cid, {})
            rate = v.get("samples_per_s") or 0.0
            crate = v.get("compute_samples_per_s") or 0.0
            ratio = (min(1.0, rate / crate)
                     if rate > 0 and crate > 0 else 1.0)
            row = label_of[cid]
            if row.shape[0] < n_classes:
                row = np.pad(row, (0, n_classes - row.shape[0]))
            feats[cid] = np.concatenate([row, [ratio]])
        return feats

    @staticmethod
    def _medians(views: dict, fleet: dict | None = None
                 ) -> tuple[float | None, float | None]:
        """Fleet rate / compute-rate medians.  Under the digest
        roll-up the exact views are a BIASED slice (watchlist = the
        worst clients), so the medians come from the merged digest's
        quantile sketches instead — the whole fleet, within one
        bucket width."""
        dig = (fleet or {}).get("digest") or {}
        q = dig.get("quantiles") or {}
        if q.get("rate_p50") is not None:
            return q.get("rate_p50"), q.get("crate_p50")
        rates = [v.get("samples_per_s") for v in views.values()
                 if v.get("samples_per_s") and v.get("state") != "lost"]
        crates = [v.get("compute_samples_per_s")
                  for v in views.values()
                  if v.get("compute_samples_per_s")
                  and v.get("state") != "lost"]
        return (statistics.median(rates) if rates else None,
                statistics.median(crates) if crates else None)

    def _attribute(self, v: dict, med, cmed) -> str:
        """Why is this client slow: ``stale`` (version lag), `
        ``compute`` (device rate trails the fleet), ``wire`` (device
        rate healthy, end-to-end rate is not), else ``unknown``."""
        lag = v.get("version_lag")
        if lag is not None and lag >= 2:
            return "stale"
        crate = v.get("compute_samples_per_s")
        if crate and cmed:
            if crate < SLOW_SCORE * cmed:
                return "compute"
            rate = v.get("samples_per_s")
            if rate is not None and med and rate < SLOW_SCORE * med:
                return "wire"
        return "unknown"

    # -- decision sites (every _act_* MUST journal — slcheck SC001) ----------

    def _act_demote(self, cid: str, attribution: str,
                    round_idx: int) -> None:
        """Grant per-client knob retunes instead of one global config:
        wire-slow gets a heavier activation codec (its round is wire
        bytes); compute/stale-slow gets a wider bounded-staleness
        window and a quorum exemption (its contribution folds late
        instead of holding the fleet)."""
        if attribution == "wire":
            knobs: dict[str, Any] = {
                "codec": {"intermediate": self.sch.wire_slow_codec}}
            why = (f"wire-slow: retuned intermediate codec to "
                   f"{self.sch.wire_slow_codec}")
        elif attribution in ("compute", "stale"):
            knobs = {"staleness_bonus": self.sch.staleness_bonus,
                     "quorum_exempt": True}
            self._stale_bonus[cid] = self.sch.staleness_bonus
            self._exempt.add(cid)
            why = (f"{attribution}-slow: staleness window "
                   f"+{self.sch.staleness_bonus}, quorum-exempt")
        else:
            knobs = {"quorum_exempt": True}
            self._exempt.add(cid)
            why = "slow (unattributed): quorum-exempt"
        self._knobs[cid] = knobs
        if self.faults is not None:
            self.faults.inc("sched_demotions")
        self.journal("demote", round_idx, client=cid, why=why,
                     detail={"attribution": attribution,
                             "knobs": knobs})

    def _act_promote(self, cid: str, round_idx: int,
                     boundaries: int) -> None:
        """Revoke a demotion after a sustained recovery: the client
        has scored healthy for as many consecutive boundaries as the
        evict ladder requires (symmetric hysteresis — one good
        boundary must not flap the knobs off, a transient blip must
        not degrade wire fidelity forever).  The next START ships
        ``sched: None`` and the client reverts to its config codecs."""
        self._knobs.pop(cid, None)
        self._stale_bonus.pop(cid, None)
        self._exempt.discard(cid)
        self._healthy.pop(cid, None)
        self.journal(
            "promote", round_idx, client=cid,
            why=f"healthy for {boundaries} consecutive boundaries: "
                "demotion knobs revoked",
            detail={"boundaries": boundaries})

    def _act_evict(self, cid: str, round_idx: int,
                   boundaries: int) -> None:
        """Evict a persistent straggler through the elastic-drop path
        (the server publishes STOP, reclaims its shadow and forgets
        its telemetry; a later re-REGISTER rejoins it)."""
        self._evicted.add(cid)
        self._forget(cid)
        if self.faults is not None:
            self.faults.inc("sched_evictions")
        self.journal(
            "evict", round_idx, client=cid,
            why=f"straggler for {boundaries} consecutive boundaries "
                f"(>= evict-after {self.sch.evict_after})",
            detail={"boundaries": boundaries})

    def _act_replan(self, plan: ClusterPlan, result: dict,
                    round_idx: int) -> None:
        """Adopt a measured-throughput cut re-plan for one cluster
        (ships through the existing re-plan/START machinery: the
        server marks every member whose layer range moved for a full
        re-seed)."""
        self._last_replan_round = round_idx
        self.last_replan = {
            "round": round_idx, "cluster": plan.cluster_id,
            "cuts_from": list(plan.cuts), "cuts_to": result["cuts"],
            "improvement": result["improvement"]}
        if self.faults is not None:
            self.faults.inc("sched_replans")
        self.journal(
            "replan", round_idx, cluster=plan.cluster_id,
            why=(f"predicted round wall improves "
                 f"{result['improvement']:.0%} (>= damping "
                 f"{self.sch.replan_damping:.0%})"),
            detail={"cuts_from": list(plan.cuts),
                    "cuts_to": list(result["cuts"]),
                    "predicted_wall_s": result["predicted_wall_s"],
                    "incumbent_wall_s": result["incumbent_wall_s"],
                    "improvement": result["improvement"]})

    def _act_retune_fanin(self, old: int, new: int, round_idx: int,
                          model: dict) -> None:
        """Adopt a measured-fold-wall aggregator fan-in retune: the
        next round's tree is planned with ``new`` members per group.
        Damped like cut re-planning (adopted only when the predicted
        critical-path fold wall improves by ``replan-damping``) and
        cooled down on the same knob, so tree shape cannot flap."""
        self._fan_in = int(new)
        self._last_fanin_round = round_idx
        if self.faults is not None:
            self.faults.inc("sched_fanin_retunes")
        self.journal(
            "retune", round_idx,
            why=(f"measured agg_node fold walls: fan-in {old} -> "
                 f"{new} improves the predicted tree critical path "
                 f"{model['improvement']:.0%} (>= damping "
                 f"{self.sch.replan_damping:.0%})"),
            detail={"fan_in_from": int(old), "fan_in_to": int(new),
                    **model})

    def _agg_node_fold_cost(self, fleet: dict
                            ) -> tuple[float | None, int]:
        """Measured per-contribution fold wall (seconds) from the
        ``kind=agg_node`` heartbeat views' gauges, plus the reporting
        node count.  None until at least one node reported a round's
        fold numbers."""
        fold_s = folded = 0.0
        nodes = 0
        for cid in sorted((fleet.get("clients") or {})):
            v = fleet["clients"][cid]
            if v.get("kind") != "agg_node" or v.get("state") == "lost":
                continue
            g = v.get("gauges") or {}
            f, n = g.get("agg_node_fold_s"), g.get("agg_node_folded")
            if not f or not n:
                continue
            fold_s += float(f)
            folded += float(n)
            nodes += 1
        if folded <= 0:
            return None, nodes
        return fold_s / folded, nodes

    @staticmethod
    def _tree_wall(fan_in: int, n: int, per_fold_s: float,
                   levels: int) -> float:
        """Predicted critical-path fold wall of the tree plan_tree
        actually builds over ``n`` leaves: depth is CAPPED at
        ``aggregation.levels`` (narrower fan-in does not buy depth
        past it), each level's node folds fan_in children
        sequentially and the levels cascade, and the ROOT then folds
        every top-level partial itself — ceil(n / fan_in^depth) of
        them, the term that punishes a too-narrow tree at a shallow
        levels cap instead of rewarding it."""
        import math
        f = max(fan_in, 2)
        depth = max(1, min(int(levels), math.ceil(
            math.log(max(n, 2)) / math.log(f))))
        top = math.ceil(n / (f ** depth))
        return (depth * f + top) * per_fold_s

    def _retune_fanin(self, plans: list, round_idx: int,
                      fleet: dict) -> int | None:
        """Scan the candidate fan-ins against the measured per-fold
        cost; adopt the argmin under damping + cooldown."""
        cur = self._fan_in
        if not self.sch.retune_fanin or cur < 2:
            return None
        cooled = (self._last_fanin_round is None
                  or round_idx - self._last_fanin_round
                  > self.sch.replan_cooldown)
        if not cooled:
            return None
        per_fold, _nodes = self._agg_node_fold_cost(fleet)
        if per_fold is None:
            return None   # no measured agg_node round yet
        n = sum(len(p.stage1_clients) for p in plans)
        if n <= cur:
            return None   # the tree is degenerate at this population
        levels = int(getattr(self.cfg.aggregation, "levels", 1) or 1)
        incumbent = self._tree_wall(cur, n, per_fold, levels)
        best, best_wall = cur, incumbent
        for f in FANIN_CANDIDATES:
            if f >= n:
                continue
            w = self._tree_wall(f, n, per_fold, levels)
            if w < best_wall:
                best, best_wall = f, w
        if best == cur:
            return None
        improvement = (incumbent - best_wall) / incumbent
        if improvement < self.sch.replan_damping:
            return None
        self._act_retune_fanin(cur, best, round_idx, {
            "fold_ms_per_contrib": round(per_fold * 1e3, 6),
            "members": n,
            "predicted_wall_s": round(best_wall, 6),
            "incumbent_wall_s": round(incumbent, 6),
            "improvement": round(improvement, 4)})
        return best

    def _act_drop(self, cid: str, round_idx: int, state: str,
                  waited_s: float) -> None:
        """Mid-round barrier drop: the round stops waiting for a
        health-state-straggler past the grace window (its late Update
        still folds through the staleness window in async mode)."""
        if self.faults is not None:
            self.faults.inc("sched_barrier_drops")
        self.journal(
            "drop", round_idx, client=cid,
            why=(f"barrier waited {waited_s:.1f}s > grace "
                 f"{self.sch.barrier_grace_s:g}s for a {state} "
                 "client"),
            detail={"state": state, "waited_s": round(waited_s, 3)})

    def _act_cluster_move(self, cid: str, src, dst,
                          round_idx: int) -> None:
        """One client crossed the hysteresis margin into another
        online cluster."""
        if self.faults is not None:
            self.faults.inc("sched_cluster_moves")
        self.journal(
            "cluster", round_idx, client=cid, cluster=dst,
            why=f"feature drift past hysteresis "
                f"{self.sch.hysteresis:g} (from cluster {src})",
            detail={"from": src, "to": dst})

    # -- the boundary pass ---------------------------------------------------

    def plan_round(self, plans: list, round_idx: int, fleet: dict,
                   profiles: dict | None = None) -> SchedOutcome:
        """One closed-loop pass: observe → cluster → score → act.
        Deterministic given (plans, fleet, profiles, seed)."""
        t0 = time.perf_counter()
        out = SchedOutcome(round_idx=round_idx, evict=set(),
                           plans=None)
        views = self._views(fleet)
        acting = (round_idx >= self.sch.warmup_rounds
                  and (round_idx % self.sch.interval) == 0)

        # (a) online clustering — always observes (the map must track
        # the fleet through warmup), moves journal once acting
        prev = dict(self.clusterer.assignment)
        feats = self._features(plans, views)
        assignment, moved = self.clusterer.update(feats, round_idx)
        if self.gauges is not None:
            self.gauges.set("sched_clusters",
                            len(set(assignment.values())))
        if acting:
            for cid in moved:
                self._act_cluster_move(cid, prev.get(cid),
                                       assignment[cid], round_idx)

        # per-stage measured stats (telemetry snapshot "stages":
        # direct reporters + digest sketches) — what the cut
        # re-planner uses instead of mirroring stage-1 profiles
        self._stage_stats = fleet.get("stages") or {}

        # (b) straggler policy
        med, cmed = self._medians(views, fleet)
        evict: set = set()
        evict_n: dict[str, int] = {}
        if acting:
            for cid in sorted(views):
                v = views[cid]
                straggling = v.get("state") in ("straggler", "lost")
                if not straggling:
                    score = v.get("straggler_score")
                    straggling = (score is not None
                                  and score < SLOW_SCORE)
                if not straggling:
                    if self._ledger.pop(cid, None) is not None \
                            and self.log is not None:
                        self.log.info(
                            f"sched: {cid} recovered (ledger reset)",
                            "green")
                    if cid in self._knobs or cid in self._exempt:
                        # promote-side hysteresis, symmetric with the
                        # evict ladder: the demotion is revoked only
                        # after evict-after consecutive HEALTHY
                        # boundaries — one good boundary must not
                        # flap the knobs off
                        streak = self._healthy[cid] = \
                            self._healthy.get(cid, 0) + 1
                        if streak >= self.sch.evict_after:
                            self._act_promote(cid, round_idx, streak)
                    continue
                self._healthy.pop(cid, None)
                n = self._ledger[cid] = self._ledger.get(cid, 0) + 1
                if self.sch.evict and n >= self.sch.evict_after:
                    evict.add(cid)
                    evict_n[cid] = n
                elif self.sch.demote and cid not in self._knobs:
                    self._act_demote(cid, self._attribute(v, med,
                                                          cmed),
                                     round_idx)
        new_plans = plans
        changed = False
        if evict:
            # feasibility BEFORE the journal: an eviction that cannot
            # be applied must never be recorded (or counted) as one
            pruned = prune_plan_members(plans, evict)
            if pruned is None:
                # dropping these members would empty a pipeline
                # stage, and an empty stage cannot run
                self.journal(
                    "evict-skip", round_idx,
                    why="eviction would empty a pipeline stage; "
                        "demoting instead",
                    detail={"clients": sorted(evict)})
                for cid in sorted(evict):
                    self._ledger[cid] = self.sch.evict_after - 1
                    if self.sch.demote and cid not in self._knobs:
                        self._act_demote(
                            cid, self._attribute(views[cid], med,
                                                 cmed), round_idx)
                evict = set()
            else:
                new_plans, changed = pruned, True
                for cid in sorted(evict):
                    self._act_evict(cid, round_idx, evict_n[cid])
        out.evict = evict

        # (c) measured-throughput cut re-planning, damped + cooled
        if acting and self.sch.replan:
            cooled = (self._last_replan_round is None
                      or round_idx - self._last_replan_round
                      > self.sch.replan_cooldown)
            if cooled:
                replanned = []
                for p in new_plans:
                    res = self._replan_plan(p, views, profiles or {})
                    if res is not None and res["adopted"]:
                        self._act_replan(p, res, round_idx)
                        p = dataclasses.replace(
                            p, cuts=list(res["cuts"]))
                        changed = True
                    replanned.append(p)
                new_plans = replanned

        # (d) aggregator fan-in retuning from measured kind=agg_node
        # fold walls (the other open 1M-tier control loop), damped and
        # cooled like cut re-planning
        if acting:
            out.fan_in = self._retune_fanin(new_plans, round_idx,
                                            fleet)

        out.plans = new_plans if changed else None
        out.decision_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if self.gauges is not None:
            self.gauges.set("sched_decision_ms", out.decision_ms)
        self._last_decide_round = round_idx
        if acting:
            self._last_acting_round = round_idx
        self.journal(
            "decide", round_idx,
            why="boundary pass",
            detail={"clients": len(views), "acting": acting,
                    "evicted": sorted(evict),
                    # THIS boundary's demotions, not the cumulative
                    # demoted population — the decide stream must say
                    # when control actions actually happened
                    "demoted": sum(
                        1 for d in list(self.decisions)
                        if d["action"] == "demote"
                        and d["round"] == round_idx),
                    "moves": len(moved) if acting else 0,
                    "decision_ms": out.decision_ms})
        return out

    def _replan_plan(self, plan: ClusterPlan, views: dict,
                     profiles: dict) -> dict | None:
        """Measured inputs for one cluster's cut search: the profile's
        per-layer shape + boundary bytes, rescaled to each member's
        measured device rate, with the wire bandwidth implied by its
        measured end-to-end/device rate gap at the CURRENT cut."""
        if plan.n_stages < 2 or not plan.cuts:
            return None
        from split_learning_tpu.planner.throughput import (
            implied_bandwidth, replan_cuts, scaled_exe_time,
        )
        members = list(plan.stage1_clients)
        # bound the per-boundary model cost: rates add harmonically
        # across members, so an evenly-strided subsample scales BOTH the
        # incumbent's and every candidate's predicted rate by the same
        # factor — the argmin and the improvement ratio the damping
        # gate reads are unchanged, while a 1k-member cluster costs
        # the same as a 64-member one
        if len(members) > self.REPLAN_MEMBER_SAMPLE:
            stride = len(members) / self.REPLAN_MEMBER_SAMPLE
            members = [members[int(i * stride)]
                       for i in range(self.REPLAN_MEMBER_SAMPLE)]
        profs = [(profiles.get(c) or {}) for c in members]
        size_data = next((p["size_data"] for p in profs
                          if p.get("size_data")), None)
        base_exe = next((p["exe_time"] for p in profs
                         if p.get("exe_time")), None)
        if size_data is None or base_exe is None:
            return None   # nothing to model transfer bytes against
        wire_factor = {"float32": 1.0, "float16": 0.5,
                       "bfloat16": 0.5, "int8": 0.25}[
                           self.cfg.transport.wire_dtype_normalized]
        size_data = [float(s) * wire_factor for s in size_data]
        cur_cut_bytes = size_data[int(plan.cuts[0]) - 1]
        exe, nets = [], []
        for c, p in zip(members, profs):
            v = views.get(c, {})
            exe.append(scaled_exe_time(
                p.get("exe_time") or base_exe,
                v.get("compute_samples_per_s")))
            bw = implied_bandwidth(cur_cut_bytes,
                                   v.get("samples_per_s"),
                                   v.get("compute_samples_per_s"))
            if not bw:
                bw = float(p.get("network") or 0.0)
            nets.append(bw)
        n_groups = plan.n_stages
        # later stages: the profile never covered them (the reference
        # keeps only stage-1 size_data), but the telemetry plane now
        # MEASURES them — each stage's clients report compute rate and
        # step wall on their heartbeats, rolled up per stage in the
        # fleet snapshot's "stages" block.  Build each group from its
        # members' measured rates (stage-median fallback for quiet
        # members); a stage with no measurements at all mirrors
        # group 1, the pre-digest behavior.
        exe_groups, net_groups = [exe], [nets]
        for k in range(2, n_groups + 1):
            stage_crate = (self._stage_stats.get(str(k)) or {}).get(
                "compute_samples_per_s_p50")
            members_k = list(plan.clients[k - 1])
            if len(members_k) > self.REPLAN_MEMBER_SAMPLE:
                stride = len(members_k) / self.REPLAN_MEMBER_SAMPLE
                members_k = [members_k[int(i * stride)]
                             for i in range(self.REPLAN_MEMBER_SAMPLE)]
            g_exe, g_nets, measured = [], [], False
            for c in members_k:
                v = views.get(c, {})
                crate = v.get("compute_samples_per_s") or stage_crate
                if crate:
                    measured = True
                g_exe.append(scaled_exe_time(base_exe, crate))
                bw = implied_bandwidth(cur_cut_bytes,
                                       v.get("samples_per_s"),
                                       v.get("compute_samples_per_s"))
                g_nets.append(bw or 0.0)
            if measured and g_exe:
                exe_groups.append(g_exe)
                net_groups.append(g_nets)
            else:
                exe_groups.append(exe)
                net_groups.append(nets)
        return replan_cuts(exe_groups, net_groups,
                           size_data, plan.cuts,
                           damping=self.sch.replan_damping)

    # -- in-round queries ----------------------------------------------------

    def knobs_for(self, cid: str) -> dict | None:
        """The per-client knob frame riding START ``extra.sched``."""
        return self._knobs.get(cid)

    def staleness_bonus_for(self, cid: str) -> int:
        return self._stale_bonus.get(cid, 0)

    @property
    def max_staleness_bonus(self) -> int:
        """Upper bound of any granted bonus — sizes the server's
        (client, version) dedup-ledger retention."""
        return max(self._stale_bonus.values(), default=0)

    def quorum_exempt(self, cid: str) -> bool:
        return cid in self._exempt

    def attention(self) -> set:
        """Clients under active scheduler control (knob-carrying,
        exempted, or on the eviction ladder): what the server pins to
        the FleetMonitor watchlist under the digest roll-up, so this
        loop keeps an exact view of everyone it is acting on."""
        return (set(self._knobs) | self._exempt
                | set(self._ledger))

    def barrier_drop(self, missing: set, states: dict,
                     waited_s: float, round_idx: int) -> set:
        """Mid-round policy: which of the clients a barrier is still
        waiting on should it stop waiting for NOW.  Only health-state
        stragglers, only past the grace window — a healthy-but-
        briefly-quiet client is never dropped here."""
        # barrier-grace-s is the ONE control for mid-round drops
        # (0 = never), independent of the evict switch: an operator
        # forbidding evictions must still be able to keep barriers
        # from stalling on a health-state straggler
        if (self.sch.barrier_grace_s <= 0
                or waited_s < self.sch.barrier_grace_s
                or self._last_acting_round is None):
            return set()
        drop = {cid for cid in missing
                if states.get(cid) == "straggler"}
        for cid in sorted(drop):
            self._act_drop(cid, round_idx, states.get(cid, "?"),
                           waited_s)
        return drop

    def _forget(self, cid: str) -> None:
        self._ledger.pop(cid, None)
        self._knobs.pop(cid, None)
        self._stale_bonus.pop(cid, None)
        self._exempt.discard(cid)
        self.clusterer.assignment.pop(cid, None)

    # -- views ---------------------------------------------------------------

    def annotate_fleet(self, snap: dict) -> dict:
        """Stamp a FleetMonitor snapshot with the scheduler view: the
        ``scheduler`` block plus per-client ``cluster``/``sched``
        fields.  The ONE place the view shape lives — shared by the
        ``/fleet`` endpoint, the journaled ``kind=fleet`` record and
        the chaos cell's artifact."""
        topo = self.topology()
        snap["scheduler"] = topo
        for cid, c in (snap.get("clients") or {}).items():
            c["cluster"] = topo["clusters"].get(cid)
            c["sched"] = topo["actions"].get(cid)
        return snap

    def topology(self) -> dict:
        """The ``/fleet`` scheduler view: current cluster map, last
        per-client action, the last adopted re-plan, and the recent
        decision journal tail.  Lock-guarded — the exporter's HTTP
        threads call this while the protocol thread journals."""
        with self._lock:
            return {
                "clusters": dict(self.clusterer.assignment),
                "actions": dict(self.last_action),
                "last_replan": self.last_replan,
                "fan_in": self._fan_in,
                "decisions": list(self.decisions)[-64:],
            }


