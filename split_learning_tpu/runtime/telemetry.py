"""Live fleet telemetry plane: heartbeats, health scoring, exporters.

Everything observability built so far is *post hoc*: span journals are
merged into Perfetto after the run, ``metrics.jsonl`` is appended per
round, and a dead client is only discovered when a barrier's 600 s
deadline expires.  The closed-loop scheduler (ROADMAP item 1) and the
async mode (item 2) both need *live* sensing — per-client liveness,
rate and lag measured continuously.  This module is that plane:

* :class:`GaugeSet` — last-value-semantics named gauges joining the
  counter/histogram registries in ``runtime/trace.py``
  (:data:`~split_learning_tpu.runtime.trace.GAUGE_NAMES`, enforced by
  the ``counters`` slcheck analyzer on every ``.set`` site);
* :class:`TelemetrySnapshot` / :class:`TelemetryEmitter` — one
  participant's full telemetry state (counters, gauges, histogram
  digests, current round, EWMA samples/s) built on demand and
  published as a ``Heartbeat`` control frame on the rpc queue family
  by a background thread every ``observability.heartbeat-interval``
  seconds (and piggybacked on every Update frame, so sync rounds get
  telemetry for free).  Counter snapshots ride EVERY heartbeat, so a
  client that crashes mid-round loses at most one interval of
  counters, not the whole round;
* :class:`FleetMonitor` — the server-side consumer: per-client
  ring-buffer time series and a health state machine
  (``healthy → degraded → straggler → lost``) driven by missed
  heartbeats and percentile-relative step-rate scoring.  Duplicate or
  reordered heartbeats (chaos, redelivery) are rejected by a
  seq/send-time staleness guard so they can never flap a ``lost``
  client back to ``healthy``; genuine recovery climbs back through
  ``degraded`` (hysteresis).  The server's barriers consult
  :meth:`FleetMonitor.advance` so a ``lost`` client is dropped after
  ``observability.liveness-timeout`` seconds instead of stalling the
  round until the 600 s RPC deadline;
* **hierarchical digest roll-up** (``observability.digest-interval``,
  ``runtime/sketch.py``): aggregator nodes run this same
  :class:`FleetMonitor` over their routed clients' heartbeats and
  publish one mergeable ``FleetDigest`` per interval
  (:meth:`FleetMonitor.build_digest`); the server folds them
  (:meth:`FleetMonitor.note_digest`, seq-guarded like heartbeats) and
  keeps exact per-client state only for a bounded **watchlist**
  (digest top-K / recent transitions / scheduler pins, with
  promotion/demotion hysteresis) — rpc ingest, monitor state and the
  decision loop's input all go O(nodes + watchlist) instead of
  O(clients);
* :func:`render_prometheus` / :func:`lint_prometheus` — Prometheus
  text-format exposition (and a pure-python format linter for tests);
  per-client series are bounded by ``observability.max-client-series``
  (watchlist/worst first) with fleet-level quantile families from the
  merged digest sketch;
* :class:`TelemetryExporter` — a tiny stdlib HTTP thread serving
  ``/metrics`` (Prometheus text) and ``/fleet`` (JSON snapshot),
  polled by ``tools/sl_top.py`` for the live terminal view.

No jax, no protocol imports: the emitter publishes through a callback
the client provides, so this module stays import-light and the wire
vocabulary stays owned by ``runtime/protocol.py``.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import re
import statistics
import threading
import time
from typing import Any, Callable

from split_learning_tpu.runtime.trace import GAUGE_NAMES


class GaugeSet:
    """Thread-safe named gauges (last value wins), the third leg of the
    ``trace.py`` registry family: :class:`~split_learning_tpu.runtime
    .trace.FaultCounters` count, :class:`~split_learning_tpu.runtime
    .trace.HistogramSet` distributes, gauges *state*.  Names must come
    from :data:`~split_learning_tpu.runtime.trace.GAUGE_NAMES`
    (statically enforced by the ``counters`` analyzer, CT003)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: dict[str, float] = {}

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = float(value)

    def get(self, name: str, default: float | None = None
            ) -> float | None:
        with self._lock:
            return self._values.get(name, default)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._values)


@dataclasses.dataclass
class TelemetrySnapshot:
    """One participant's full telemetry state at one instant.

    Travels the wire as a PLAIN DICT (:meth:`as_dict`): the protocol's
    restricted unpickler admits builtins, not this class — keeping the
    wire vocabulary closed is worth the round-trip through ``dict``.
    ``seq`` increases monotonically per emitter; together with ``t``
    (the sender's clock) it is the receiver's staleness guard against
    duplicated/reordered heartbeats."""

    part: str                       # participant id
    t: float                        # sender clock (epoch seconds)
    seq: int                        # per-emitter monotonic sequence
    kind: str = "client"            # participant role: client | agg_node
    # pipeline stage this participant runs (1-based; None for
    # non-training roles): what lets the digest path and the
    # scheduler's cut re-planner aggregate MEASURED step times per
    # stage instead of mirroring stage-1 profiles
    stage: int | None = None
    round: int | None = None        # current round index (gauge)
    samples: int = 0                # cumulative samples this round
    samples_per_s: float = 0.0      # EWMA training throughput
    gauges: dict = dataclasses.field(default_factory=dict)
    counters: dict = dataclasses.field(default_factory=dict)
    wire: dict = dataclasses.field(default_factory=dict)
    latency: dict = dataclasses.field(default_factory=dict)
    v: int = 1                      # schema version

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TelemetrySnapshot | None":
        """Tolerant decode: a foreign/newer snapshot degrades to None,
        never raises into the server's rpc pump."""
        if not isinstance(d, dict):
            return None
        known = {f.name for f in dataclasses.fields(cls)}
        try:
            return cls(**{k: v for k, v in d.items() if k in known})
        except (TypeError, ValueError):
            return None


class TelemetryEmitter:
    """Client-side heartbeat publisher + EWMA rate meter.

    ``send`` is a callable taking the snapshot *dict* — the client
    wraps it in a ``Heartbeat`` frame and publishes on the rpc queue
    (keeping this module protocol-free).  ``samples_fn`` reads the
    owner's cumulative sample counter; per-round resets are handled
    (a negative delta restarts the window).  The background thread is
    a daemon started on the first START and stopped with the client;
    publish failures are counted (``heartbeat_errors``) and a run of
    consecutive failures stops the thread — a dead transport must not
    spin a hot error loop."""

    #: consecutive publish failures before the beat thread gives up
    MAX_ERRORS = 3
    #: EWMA smoothing factor per tick (~3-tick half life)
    ALPHA = 0.3

    def __init__(self, participant: str, send: Callable[[dict], None],
                 interval: float, faults=None, wire=None, hists=None,
                 gauges: GaugeSet | None = None,
                 samples_fn: Callable[[], int] | None = None,
                 kind: str = "client", stage: int | None = None):
        self.participant = participant
        # participant role stamped on every snapshot: the FleetMonitor
        # rate-scores only kind="client" reporters (an idle aggregator
        # node's 0 samples/s is its normal state, not a straggler)
        self.kind = kind
        # pipeline stage (mutable: a re-plan may move this client);
        # stamped on every snapshot for per-stage fleet aggregation
        self.stage = stage
        self.interval = float(interval)
        self._send = send
        self._faults = faults
        self._wire = wire
        self._hists = hists
        self.gauges = gauges if gauges is not None else GaugeSet()
        self._samples_fn = samples_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._samples = 0           # fallback counter (note_samples)
        self._rate: float | None = None
        self._last_t: float | None = None
        self._last_total = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- rate meter ----------------------------------------------------------

    def note_samples(self, n: int) -> None:
        """Count trained samples (only needed when no ``samples_fn``)."""
        with self._lock:
            self._samples += int(n)

    def _total_samples(self) -> int:
        if self._samples_fn is not None:
            try:
                return int(self._samples_fn())
            except Exception:  # noqa: BLE001 — a racing reset must not
                return 0       # kill the beat thread
        with self._lock:
            return self._samples

    def _tick_rate(self, now: float) -> float:
        total = self._total_samples()
        with self._lock:
            if self._last_t is None:
                inst = 0.0
            else:
                delta = total - self._last_total
                if delta < 0:           # per-round counter reset
                    delta = total
                inst = delta / max(now - self._last_t, 1e-9)
            self._last_t, self._last_total = now, total
            self._rate = (inst if self._rate is None
                          else (1 - self.ALPHA) * self._rate
                          + self.ALPHA * inst)
            rate = self._rate
        self.gauges.set("samples_per_s", round(rate, 3))
        return rate

    # -- snapshots -----------------------------------------------------------

    def snapshot(self, now: float | None = None) -> TelemetrySnapshot:
        """Build (and rate-tick) one snapshot; also used to piggyback
        telemetry on Update frames, so sync rounds report for free."""
        now = time.time() if now is None else now
        rate = self._tick_rate(now)
        # flight-recorder health rides every heartbeat: ring depth and
        # dump recency reach /fleet (sl_top's BLACKBOX column) without
        # a new frame kind.  -1 age = recorder on, never dumped.
        from split_learning_tpu.runtime import blackbox
        if blackbox.enabled():
            self.gauges.set("blackbox_ring_depth", blackbox.depth())
            age = blackbox.last_dump_age()
            self.gauges.set("blackbox_last_dump_age_s",
                            -1.0 if age is None else round(age, 1))
        with self._lock:
            self._seq += 1
            seq = self._seq
        rnd = self.gauges.get("round")
        return TelemetrySnapshot(
            part=self.participant, t=now, seq=seq, kind=self.kind,
            stage=self.stage,
            round=None if rnd is None else int(rnd),
            samples=self._total_samples(),
            samples_per_s=round(rate, 3),
            gauges=self.gauges.snapshot(),
            counters=(self._faults.snapshot() if self._faults else {}),
            wire=({k: v for k, v in self._wire.snapshot().items() if v}
                  if self._wire else {}),
            latency=(self._hists.snapshot() if self._hists else {}))

    def beat_once(self) -> None:
        self._send(self.snapshot().as_dict())

    # -- thread lifecycle ----------------------------------------------------

    def start(self) -> None:
        """Idempotent; no-op when the interval disables heartbeats."""
        if self.interval <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"heartbeat-{self.participant}")
        self._thread.start()

    def _run(self) -> None:
        errors = 0
        while not self._stop.wait(self.interval):
            try:
                self.beat_once()
                errors = 0
            except Exception as e:  # noqa: BLE001 — transport gone/
                # teardown.  A scripted ChaosCrash is the simulated
                # process dying — stop beating IMMEDIATELY (the sticky
                # crashed transport kills the training thread at its
                # next op); retrying would mis-model a dead process as
                # three more liveness signals.  Matched by name so the
                # telemetry plane keeps zero chaos imports.
                if type(e).__name__ == "ChaosCrash":
                    return
                errors += 1
                if self._faults is not None:
                    self._faults.inc("heartbeat_errors")
                if errors >= self.MAX_ERRORS:
                    return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.interval))
            self._thread = None


# --------------------------------------------------------------------------
# server-side fleet monitor
# --------------------------------------------------------------------------

#: ring-buffer length of the per-client (t, rate, samples) series
HISTORY = 256

HEALTH_STATES = ("healthy", "degraded", "straggler", "lost")
_STATE_CODE = {s: i for i, s in enumerate(HEALTH_STATES)}


@dataclasses.dataclass
class _ClientHealth:
    state: str = "healthy"
    kind: str = "client"            # client | agg_node (snapshot.kind)
    stage: int | None = None        # pipeline stage (snapshot.stage)
    # digest roll-up: the aggregator node whose FleetDigest sourced
    # this entry (a WATCHLIST member — its liveness/state machine runs
    # on that node, the server keeps the exact view), None for clients
    # heartbeating directly at this monitor
    via: str | None = None
    first_seen: float = 0.0
    last_seen: float = 0.0          # receiver clock, any FRESH frame
    last_t_send: float = 0.0        # sender clock of last fresh beat
    last_seq: int = -1
    rate: float | None = None       # EWMA samples/s (sender-reported)
    score: float | None = None      # rate / fleet median (lower=worse)
    round: int | None = None
    version: int | None = None      # last Update's seed version (async)
    samples: int = 0
    counters: dict = dataclasses.field(default_factory=dict)
    wire: dict = dataclasses.field(default_factory=dict)
    latency: dict = dataclasses.field(default_factory=dict)
    gauges: dict = dataclasses.field(default_factory=dict)
    series: collections.deque = dataclasses.field(
        default_factory=lambda: collections.deque(maxlen=HISTORY))


class FleetMonitor:
    """Per-client health state machine + time series, fed by the
    server's rpc pump and advanced on a wall clock.

    State machine (``healthy → degraded → straggler → lost``):

    * *missed heartbeats*: silence past ``DEGRADED_MISSES`` intervals
      degrades, past ``STRAGGLER_MISSES`` intervals marks a straggler,
      past ``liveness_timeout`` seconds marks **lost** — the state the
      server's barriers are allowed to drop;
    * *step-rate scoring*: a reporting client whose EWMA samples/s
      falls below ``STRAGGLER_SCORE`` × the fleet median is a
      straggler even while heartbeating on time (the slow-but-alive
      case eviction must SEE but not kill — that policy belongs to the
      scheduler, ROADMAP item 1);
    * *recovery*: fresh contact lifts ``lost`` only to ``degraded``;
      the next :meth:`advance` with recent contact and a score at or
      above ``RECOVER_SCORE`` × median completes the climb to
      ``healthy``.  The two-step path plus the seq/send-time staleness
      guard (duplicated or reordered heartbeats are dropped and
      counted ``stale_heartbeats``) is what keeps chaos dup/reorder
      from flapping ``lost`` → ``healthy``.

    Thread-safe: the rpc pump feeds it, HTTP exporter threads read it.
    """

    DEGRADED_MISSES = 1.5    # intervals of silence -> degraded
    STRAGGLER_MISSES = 2.0   # intervals of silence -> straggler
    STRAGGLER_SCORE = 0.5    # rate below this x median -> straggler
    RECOVER_SCORE = 0.75     # rate at/above this x median -> healthy
    STALE_LAG = 2            # version lag at/above this -> straggler
    MAX_TRANSITIONS = 512    # bounded transition journal
    #: watchlist demotion hysteresis: a digest-sourced client is
    #: dropped back to sketch space only after this many consecutive
    #: digests from its node stopped naming it AND it is healthy — a
    #: client oscillating around the top-K boundary cannot flap in and
    #: out of exact state
    WATCH_DEMOTE_MISSES = 3
    #: per-digest worst-straggler fan-in (K of the top-K heap)
    DIGEST_TOP_K = 8

    def __init__(self, interval: float, liveness_timeout: float,
                 log=None, gauges: GaugeSet | None = None,
                 faults=None, watchlist_size: int = 64):
        self.interval = max(float(interval), 1e-3)
        self.liveness_timeout = float(liveness_timeout)
        self._log = log
        self._faults = faults
        self.gauges = gauges if gauges is not None else GaugeSet()
        self._lock = threading.RLock()
        self._clients: dict[str, _ClientHealth] = {}
        self._last_pump: float | None = None
        # hierarchical digest roll-up (runtime/sketch.py): latest
        # FleetDigest per aggregator node (seq-guarded), the bounded
        # watchlist's miss counters (promotion/demotion hysteresis)
        # and the pinned set (scheduler attention — never demoted
        # while pinned)
        self.watchlist_size = int(watchlist_size)
        self._digests: dict[str, dict] = {}
        self._watch_miss: dict[str, int] = {}
        self._pinned: set = set()
        # monotonic transition sequence: stamps every journal record so
        # build_digest can report exactly the transitions since the
        # previous digest, duplicate-free across digest intervals
        self._tx_seq = 0
        self._digest_mark = 0
        # async staleness as a first-class fleet signal: the server's
        # current global version (note_version at each cut) vs the
        # version each client's last Update was seeded from — the lag
        # the admission window decays by, surfaced per client as
        # sl_client_version_lag and annotated `stale` on straggler
        # transitions it causes
        self._version: int | None = None
        self.transitions: collections.deque = collections.deque(
            maxlen=self.MAX_TRANSITIONS)
        # optional hook fired (under the monitor lock) when a client
        # transitions INTO `lost`: the server prunes per-client server
        # state that would otherwise leak — today the delta codec's
        # shadow trees (runtime/server.py _on_client_lost).  Must be
        # cheap and non-blocking.
        self.on_lost = None

    # -- ingest --------------------------------------------------------------

    def _ensure(self, cid: str, now: float) -> _ClientHealth:
        h = self._clients.get(cid)
        if h is None:
            h = self._clients[cid] = _ClientHealth(
                first_seen=now, last_seen=now)
        return h

    def note_pump(self, now: float | None = None) -> None:
        """Mark the feeding queue as freshly drained.  Age-based
        transitions are only meaningful while someone is actually
        pumping the rpc queue: during a long server-side phase
        (validation, aggregation) heartbeats pile up undelivered and
        every client would LOOK silent — :meth:`advance` freezes
        age-driven downgrades whenever the last pump is stale.  Never
        calling this (standalone/unit use) leaves the gate open."""
        now = time.time() if now is None else now
        with self._lock:
            self._last_pump = now

    def note_frame(self, cid: str, now: float | None = None,
                   via: str | None = None) -> None:
        """Any rpc frame from ``cid`` proves a live process — clients
        whose config disables heartbeats still register liveness.
        ``via`` (the server's digest routing table) marks the entry
        digest-covered: a routed client's occasional control frames
        (READY/NOTIFY) must not start an aging clock here that its
        heartbeats — which go to the node — can never feed."""
        now = time.time() if now is None else now
        with self._lock:
            h = self._ensure(cid, now)
            h.last_seen = max(h.last_seen, now)
            if via is not None:
                h.via = via
                return   # state machine runs on the digest node
            if h.state == "lost":
                self._transition(cid, h, "degraded", "contact resumed",
                                 now)

    def note_heartbeat(self, cid: str, telemetry: dict | None,
                       now: float | None = None,
                       via: str | None = None) -> bool:
        """Fold one heartbeat/piggybacked snapshot; False when it was
        stale (duplicate/reordered) and therefore ignored — a stale
        beat must neither refresh liveness nor flap the state.

        ``via`` names the digest node whose roll-up covers this
        client (the server passes its routing table): the fresh data
        folds, but the entry stays digest-covered — its liveness
        clock keeps running on the node, not here, so a routed client
        whose direct frames are merely occasional (round-end Update
        piggybacks) can never age into a phantom ``lost``."""
        now = time.time() if now is None else now
        snap = TelemetrySnapshot.from_dict(telemetry or {})
        with self._lock:
            h = self._ensure(cid, now)
            if snap is None:
                h.last_seen = max(h.last_seen, now)
                return True
            # freshness is lexicographic on (sender clock, seq): a
            # duplicate ties, a reordered older beat is behind on both
            # — and a crashed-and-restarted client (new emitter, seq
            # back at 1) is STILL fresh because its clock moved on,
            # while the old emitter's late-draining frames (higher
            # seq, older clock) stay stale.  Plain seq comparison
            # would lock a restarted client out until its new seq
            # caught the old one.
            if (snap.t, snap.seq) <= (h.last_t_send, h.last_seq):
                if self._faults is not None:
                    self._faults.inc("stale_heartbeats")
                return False
            h.last_seq = snap.seq
            h.last_t_send = snap.t
            h.last_seen = max(h.last_seen, now)
            h.kind = snap.kind or "client"
            if snap.stage is not None:
                h.stage = int(snap.stage)
            # an unrouted direct heartbeat outranks the digest view:
            # the client is talking to THIS monitor again (digest-node
            # fallback), so its liveness clock runs here from now on.
            # A routed client's occasional direct frame (Update
            # piggyback) keeps its digest coverage instead.
            h.via = via
            h.rate = float(snap.samples_per_s)
            h.round = snap.round
            h.samples = int(snap.samples)
            if snap.counters:
                h.counters = dict(snap.counters)
            if snap.wire:
                h.wire = dict(snap.wire)
            if snap.latency:
                h.latency = dict(snap.latency)
            if snap.gauges:
                # the perf plane's gauges (mfu, compute rate, compile
                # seconds, HBM peak) ride every snapshot — what lets
                # the monitor and sl_top tell compute-slow from
                # wire-slow without another wire frame
                h.gauges = dict(snap.gauges)
            h.series.append((round(now, 3), h.rate, h.samples))
            if h.state == "lost":
                self._transition(cid, h, "degraded", "fresh heartbeat",
                                 now)
            return True

    def note_version(self, version: int) -> None:
        """The server cut a new global version (async mode; in sync
        mode this is simply the invocation generation)."""
        with self._lock:
            self._version = int(version)

    def note_client_version(self, cid: str, version: int,
                            now: float | None = None) -> None:
        """Record the seed version of a client's admitted Update —
        the numerator of its version lag."""
        now = time.time() if now is None else now
        with self._lock:
            h = self._ensure(cid, now)
            if h.version is None or version > h.version:
                h.version = int(version)

    def _lag(self, h: _ClientHealth) -> int | None:
        if self._version is None or h.version is None:
            return None
        return max(0, self._version - h.version)

    def forget(self, cid: str) -> None:
        """Elastic prune: a client removed from the plans stops being
        scored (and stops dragging the fleet median down)."""
        with self._lock:
            self._clients.pop(cid, None)
            self._watch_miss.pop(cid, None)
            self._pinned.discard(cid)

    # -- hierarchical digest roll-up (runtime/sketch.py) ---------------------

    def route_via(self, cid: str, node_id: str | None) -> None:
        """The server routed this client's heartbeats to a digest
        node: any standing exact entry stops aging here (the node's
        state machine covers it from now on).  No-op without an entry
        — the digest alone will carry the client."""
        if node_id is None:
            return
        with self._lock:
            h = self._clients.get(cid)
            if h is not None:
                h.via = node_id

    def watch(self, cid: str, pinned: bool = True) -> None:
        """Pin a client to the watchlist (scheduler attention: a
        demoted/knob-carrying client must keep its exact view even
        when it climbs out of the digests' top-K).  ``pinned=False``
        releases the pin; the normal demotion hysteresis then
        applies."""
        with self._lock:
            if pinned:
                self._pinned.add(cid)
                self._watch_miss.pop(cid, None)
            else:
                self._pinned.discard(cid)

    def note_digest(self, node_id: str, digest: dict | None,
                    now: float | None = None) -> bool:
        """Fold one aggregator node's FleetDigest: False when it was
        stale (duplicate/reordered — same lexicographic (t, seq) guard
        as heartbeats) or undecodable.  Fresh digests replace the
        node's standing summary wholesale (each digest is a full
        restatement, not an increment — redelivery can never
        double-count), append the node's state transitions to the
        shared journal, and run the watchlist promotion/demotion
        hysteresis over the digest's top-K."""
        from split_learning_tpu.runtime import sketch
        now = time.time() if now is None else now
        d = sketch.decode_digest(digest)
        with self._lock:
            if d is None:
                if self._faults is not None:
                    self._faults.inc("stale_digests")
                return False
            last = self._digests.get(node_id)
            if last is not None and (d["t"], d["seq"]) \
                    <= (last["t"], last["seq"]):
                if self._faults is not None:
                    self._faults.inc("stale_digests")
                return False
            self._digests[node_id] = d
            for rec in d.get("transitions") or []:
                if isinstance(rec, dict) and rec.get("client"):
                    self.transitions.append(
                        {**rec, "via": node_id})
            # -- watchlist maintenance ---------------------------------------
            mentioned: set = set()
            for w in d.get("worst") or []:
                cid = w.get("client")
                if not cid:
                    continue
                mentioned.add(cid)
                self._promote_from_view(cid, w, node_id, now)
            for rec in d.get("transitions") or []:
                cid = rec.get("client")
                if cid:
                    # a transition names the client but carries no
                    # view; promotion happens on its next top-K
                    # mention — resetting the miss counter here keeps
                    # a transitioning client from demoting mid-event
                    mentioned.add(cid)
            for cid, h in list(self._clients.items()):
                if h.via != node_id:
                    continue
                if cid in mentioned:
                    self._watch_miss.pop(cid, None)
                    continue
                miss = self._watch_miss[cid] = \
                    self._watch_miss.get(cid, 0) + 1
                # demotion hysteresis, WATCH_DEMOTE_MISSES consecutive
                # unmentioned digests.  build_digest ranks EVERY
                # client into its worst heap, so a still-straggler/
                # lost client keeps being mentioned — sustained
                # absence means the client now ranks healthier than
                # the node's top-K, and keeping the STALE severe copy
                # would freeze a recovered client in straggler/lost
                # (the scheduler would act on fiction, and the cap
                # would preferentially retain exactly these).  Pinned
                # entries stay (scheduler attention needs to SEE the
                # recovery) but their state resets to healthy.
                if miss >= self.WATCH_DEMOTE_MISSES:
                    if cid in self._pinned:
                        h.state = "healthy"
                        h.score = None
                        self._watch_miss.pop(cid, None)
                    else:
                        del self._clients[cid]
                        self._watch_miss.pop(cid, None)
            self._enforce_watchlist_cap()
            self._set_digest_gauges()
            return True

    def _promote_from_view(self, cid: str, entry: dict, node_id: str,
                           now: float) -> None:
        """Seed/refresh a watchlist entry from a digest's top-K view
        (the node runs the state machine; this is the server's exact
        copy)."""
        view = entry.get("view") or {}
        h = self._ensure(cid, now)
        h.via = node_id
        h.state = entry.get("state", h.state)
        if entry.get("score") is not None:
            h.score = entry["score"]
        h.kind = view.get("kind", h.kind) or "client"
        if view.get("stage") is not None:
            h.stage = int(view["stage"])
        if view.get("samples_per_s") is not None:
            h.rate = float(view["samples_per_s"])
        if view.get("samples") is not None:
            h.samples = int(view["samples"])
        if view.get("round") is not None:
            h.round = view["round"]
        if view.get("counters"):
            h.counters = dict(view["counters"])
        if view.get("gauges"):
            h.gauges = dict(view["gauges"])
        if view.get("latency"):
            h.latency = dict(view["latency"])
        if view.get("age_s") is not None:
            h.last_seen = max(h.last_seen, now - float(view["age_s"]))
        else:
            h.last_seen = max(h.last_seen, now)
        self._watch_miss.pop(cid, None)

    def _enforce_watchlist_cap(self) -> None:
        """Hard bound: the least-severe unpinned digest-sourced
        entries are dropped first (deterministic: severity, then id).
        Pinned entries never count against others — they ARE the
        scheduler's attention set."""
        from split_learning_tpu.runtime import sketch
        watch = [(cid, h) for cid, h in self._clients.items()
                 if h.via is not None and cid not in self._pinned]
        over = len(watch) - max(0, self.watchlist_size)
        if over <= 0:
            return
        watch.sort(key=lambda kv: sketch._worst_key(
            {"client": kv[0], "state": kv[1].state,
             "score": kv[1].score}))
        for cid, _ in watch[len(watch) - over:]:
            del self._clients[cid]
            self._watch_miss.pop(cid, None)

    def _set_digest_gauges(self) -> None:
        self.gauges.set("fleet_digest_nodes", len(self._digests))
        self.gauges.set("fleet_digest_clients",
                        sum(int(d.get("clients", 0))
                            for d in self._digests.values()))
        self.gauges.set("fleet_watchlist",
                        sum(1 for h in self._clients.values()
                            if h.via is not None))

    def drop_digest(self, node_id: str,
                    now: float | None = None) -> None:
        """Digest-node fallback (server side): forget the node's
        standing digest and convert its watchlist views to DIRECT
        entries with a fresh liveness grace — their heartbeats were
        parked on the dead node's queue, not missing, and they are
        about to resume beating here."""
        now = time.time() if now is None else now
        with self._lock:
            self._digests.pop(node_id, None)
            for cid, h in self._clients.items():
                if h.via == node_id:
                    h.via = None
                    h.last_seen = max(h.last_seen, now)
                    self._watch_miss.pop(cid, None)
            self._set_digest_gauges()

    def digest_totals(self) -> dict | None:
        """The merged cross-node digest (None when no node reported
        yet): exact state counts / counter sums / samples over every
        digest-covered client, sketch-merged quantiles, re-ranked
        worst-K."""
        from split_learning_tpu.runtime import sketch
        with self._lock:
            if not self._digests:
                return None
            return sketch.merge_digests(
                [self._digests[n] for n in sorted(self._digests)],
                k=self.DIGEST_TOP_K)

    def build_digest(self, node_id: str, seq: int,
                     now: float | None = None,
                     k: int | None = None) -> dict:
        """One digest of THIS monitor's clients — the node side of the
        roll-up (``runtime/aggnode.py DigestWorker``).  Callers should
        :meth:`advance` first so states are current.  Transitions are
        reported exactly once across successive digests (the ``i``
        cursor); per-client views ride only the top-K entries."""
        from split_learning_tpu.runtime import sketch
        now = time.time() if now is None else now
        k = self.DIGEST_TOP_K if k is None else int(k)
        with self._lock:
            d = sketch.empty_digest()
            d.update({"node": node_id, "t": round(now, 3),
                      "seq": int(seq)})
            rate, crate = sketch.ValueSketch(), sketch.ValueSketch()
            worst = sketch.WorstK(k)
            states: dict[str, int] = {}
            counters: dict[str, int] = {}
            stages: dict[str, dict] = {}
            samples = 0
            for cid, h in self._clients.items():
                if h.kind != "client":
                    continue   # nodes never digest other nodes
                d["clients"] += 1
                states[h.state] = states.get(h.state, 0) + 1
                samples += int(h.samples)
                for name, v in h.counters.items():
                    if isinstance(v, (int, float)):
                        counters[name] = counters.get(name, 0) + int(v)
                rate.observe(h.rate)
                cr = h.gauges.get("compute_samples_per_s")
                crate.observe(cr)
                step = (h.latency.get("step_device")
                        or h.latency.get("step") or {})
                if h.stage is not None:
                    ent = stages.setdefault(str(h.stage), {
                        "n": 0, "crate": sketch.ValueSketch(),
                        "step_ms": sketch.ValueSketch()})
                    ent["n"] += 1
                    ent["crate"].observe(cr)
                    ent["step_ms"].observe(step.get("p95_ms"))
                worst.add(cid, h.state, h.score,
                          view=self._digest_view(h, now))
            d["states"] = states
            d["counters"] = counters
            d["samples"] = samples
            d["rate"] = rate.as_dict()
            d["crate"] = crate.as_dict()
            d["stages"] = {
                st: {"n": e["n"], "crate": e["crate"].as_dict(),
                     "step_ms": e["step_ms"].as_dict()}
                for st, e in sorted(stages.items())}
            d["worst"] = worst.top()
            d["transitions"] = [
                t for t in self.transitions
                if t.get("i", 0) > self._digest_mark]
            if d["transitions"]:
                self._digest_mark = max(t.get("i", 0)
                                        for t in d["transitions"])
            return d

    @staticmethod
    def _digest_view(h: _ClientHealth, now: float) -> dict:
        """The compact per-client view riding a digest's top-K entry —
        what the server needs to seed a watchlist state machine."""
        step = (h.latency.get("step_device")
                or h.latency.get("step") or {})
        return {
            "kind": h.kind, "stage": h.stage,
            "samples_per_s": h.rate, "samples": h.samples,
            "round": h.round, "age_s": round(max(0.0, now
                                                 - h.last_seen), 3),
            "counters": dict(h.counters),
            "gauges": dict(h.gauges),
            "latency": ({"step_device": dict(step)} if step else {}),
        }

    # -- state machine -------------------------------------------------------

    def _transition(self, cid: str, h: _ClientHealth, to: str,
                    why: str, now: float) -> None:
        if h.state == to:
            return
        self._tx_seq += 1
        rec = {"t": round(now, 3), "client": cid, "from": h.state,
               "to": to, "why": why, "i": self._tx_seq}
        h.state = to
        self.transitions.append(rec)
        if to == "lost" and self.on_lost is not None:
            try:
                self.on_lost(cid)
            except Exception:  # noqa: BLE001 — pruning is best-effort;
                pass           # a hook bug must not kill the monitor
        if self._log is not None:
            line = (f"fleet: {cid} {rec['from']} -> {to} ({why})")
            if to == "healthy":
                self._log.info(line, "green")
            else:
                self._log.warning(line)

    @staticmethod
    def _rate_why(h: _ClientHealth, cmed: float | None) -> str:
        """Attribute a rate-scored straggler transition: a client whose
        COMPUTE rate (samples over device-busy seconds, perf-plane
        gauge) also trails the fleet is compute-slow; one whose compute
        rate is healthy is losing its round to the wire."""
        crate = h.gauges.get("compute_samples_per_s")
        if not crate or not cmed:
            return ""
        if crate < FleetMonitor.STRAGGLER_SCORE * cmed:
            return (f" (compute-slow: {crate:.1f}/s device rate vs "
                    f"fleet {cmed:.1f}/s)")
        return (f" (wire-slow: device rate healthy at {crate:.1f}/s)")

    def advance(self, now: float | None = None) -> frozenset:
        """Re-evaluate every client's time/rate-driven transitions;
        returns the current ``lost`` set (what barriers may drop)."""
        now = time.time() if now is None else now
        with self._lock:
            # pump-freshness gate (see note_pump): a stale pump means
            # silence is unmeasurable — freeze downgrades, keep the
            # standing lost set, still let resumed contact recover
            pumping = (self._last_pump is None
                       or now - self._last_pump
                       <= max(2 * self.interval, 1.0))
            # rate scoring covers TRAINING clients only: an aggregator
            # node's samples/s is structurally 0 — including it would
            # both drag the fleet median and flag the node straggler
            # for doing its job (liveness transitions still apply)
            rates = [h.rate for h in self._clients.values()
                     if h.rate and h.state != "lost"
                     and h.kind == "client"]
            med = statistics.median(rates) if rates else None
            # compute-rate median (perf-plane gauge riding heartbeats):
            # the second axis that tells a compute-slow straggler from
            # a wire-slow one in the transition journal
            crates = [h.gauges.get("compute_samples_per_s")
                      for h in self._clients.values()
                      if h.gauges.get("compute_samples_per_s")
                      and h.state != "lost"]
            cmed = statistics.median(crates) if crates else None
            if self._digests:
                # digest mode: the exact population here is the
                # watchlist + direct reporters — a biased slice (the
                # worst clients).  The fleet median must come from the
                # WHOLE fleet's sketches, or every watchlist member
                # would score against its own cohort.
                from split_learning_tpu.runtime import sketch
                rsk, csk = sketch.ValueSketch(), sketch.ValueSketch()
                for d in self._digests.values():
                    rsk.merge(d.get("rate"))
                    csk.merge(d.get("crate"))
                for h in self._clients.values():
                    if h.via is None and h.kind == "client" \
                            and h.state != "lost":
                        rsk.observe(h.rate)
                        cr = h.gauges.get("compute_samples_per_s")
                        csk.observe(cr)
                med = rsk.quantile(50) or med
                cmed = csk.quantile(50) or cmed
            lost = set()
            for cid, h in self._clients.items():
                if h.via is not None:
                    # watchlist entry: its liveness clock and state
                    # machine run on the digest node — aging it here
                    # against a clock nobody feeds would mint phantom
                    # `lost` states.  Its score still updates (the
                    # fleet median moved), and a node-reported `lost`
                    # joins the droppable set.
                    h.score = (round(h.rate / med, 4)
                               if med and h.rate is not None
                               and h.kind == "client" else h.score)
                    if h.state == "lost":
                        lost.add(cid)
                    continue
                age = now - h.last_seen
                h.score = (round(h.rate / med, 4)
                           if med and h.rate is not None
                           and h.kind == "client" else None)
                if not pumping:
                    pass
                elif age > self.liveness_timeout:
                    self._transition(
                        cid, h, "lost",
                        f"silent {age:.1f}s > liveness-timeout "
                        f"{self.liveness_timeout:g}s", now)
                elif h.state == "lost":
                    # contact resumed since the last advance
                    self._transition(cid, h, "degraded",
                                     "contact resumed", now)
                elif age > self.STRAGGLER_MISSES * self.interval:
                    self._transition(
                        cid, h, "straggler",
                        f"wire-silent: missed heartbeats "
                        f"({age:.1f}s silent)", now)
                elif age > self.DEGRADED_MISSES * self.interval:
                    if h.state == "healthy":
                        self._transition(cid, h, "degraded",
                                         "missed a heartbeat", now)
                elif (self._lag(h) is not None
                        and self._lag(h) >= self.STALE_LAG):
                    # async staleness: the client is alive and may even
                    # be fast, but its contributions fold STALE_LAG+
                    # versions behind the fleet — a distinct straggler
                    # cause from compute-slow / wire-slow
                    self._transition(
                        cid, h, "straggler",
                        f"stale: version lag {self._lag(h)} behind "
                        f"v{self._version}", now)
                elif (h.score is not None
                        and h.score < self.STRAGGLER_SCORE
                        and len(rates) >= 2):
                    self._transition(
                        cid, h, "straggler",
                        f"rate {h.rate:.1f}/s is {h.score:.2f}x the "
                        "fleet median" + self._rate_why(h, cmed), now)
                elif h.state in ("degraded", "straggler"):
                    if (h.score is None
                            or h.score >= self.RECOVER_SCORE):
                        self._transition(cid, h, "healthy",
                                         "heartbeats + rate recovered",
                                         now)
                if h.state == "lost":
                    lost.add(cid)
            counts = self._counts_locked()
            self.gauges.set("fleet_size", sum(counts.values()))
            self.gauges.set("fleet_healthy", counts.get("healthy", 0))
            self.gauges.set("fleet_degraded", counts.get("degraded", 0))
            self.gauges.set("fleet_straggler",
                            counts.get("straggler", 0))
            self.gauges.set("fleet_lost", counts.get("lost", 0))
            return frozenset(lost)

    def _counts_locked(self) -> collections.Counter:
        """Per-state fleet counts, EXACT under the digest roll-up: the
        digests' per-state counts (each node's exact state machine
        over its clients) plus the direct reporters.  Watchlist
        entries are VIEWS of digest-covered clients — counting them
        here would double-count against their node's digest."""
        counts = collections.Counter(
            h.state for h in self._clients.values() if h.via is None)
        for d in self._digests.values():
            for s, n in (d.get("states") or {}).items():
                counts[s] += int(n)
        return counts

    # -- views ---------------------------------------------------------------

    def lost(self) -> frozenset:
        with self._lock:
            return frozenset(c for c, h in self._clients.items()
                             if h.state == "lost")

    def state(self, cid: str) -> str | None:
        with self._lock:
            h = self._clients.get(cid)
            return h.state if h else None

    def states(self) -> dict:
        with self._lock:
            return {c: h.state for c, h in self._clients.items()}

    def tracked_clients(self) -> int:
        """Exact per-client entries held (direct + watchlist) — the
        count the exporter compares against max-client-series to pick
        the /fleet default shape."""
        with self._lock:
            return len(self._clients)

    def _view_of(self, cid: str, h: _ClientHealth, now: float,
                 series: bool) -> dict:
        rtt = (h.latency.get("frame_rtt") or {})
        step = (h.latency.get("step_device")
                or h.latency.get("step") or {})
        out = {
            "state": h.state,
            "kind": h.kind,
            "stage": h.stage,
            # the digest node whose roll-up sourced this entry
            # (watchlist member), None for direct reporters
            "via": h.via,
            "age_s": round(max(0.0, now - h.last_seen), 3),
            "round": h.round,
            "samples": h.samples,
            "samples_per_s": h.rate,
            "straggler_score": h.score,
            # async staleness signal: versions behind the
            # server's current cut (None outside async / before
            # the first Update)
            "version_lag": self._lag(h),
            "rtt_p95_ms": rtt.get("p95_ms"),
            "wire_bytes_out": h.wire.get("bytes_out_total"),
            # perf-plane gauges (runtime/perf.py), ridden in on
            # heartbeats; absent for clients predating the
            # plane — consumers render "-"
            "mfu": h.gauges.get("mfu"),
            "step_p95_ms": step.get("p95_ms"),
            "compute_samples_per_s":
                h.gauges.get("compute_samples_per_s"),
            "hbm_peak_bytes": h.gauges.get("hbm_peak_bytes"),
            # MPMD stage pipeline (pipeline.remote): a later-stage
            # client's ingest backlog and a stage host's slot count;
            # absent for pre-plane participants — consumers render "-"
            "queue_depth": h.gauges.get("queue_depth"),
            "stage_slots": h.gauges.get("stage_slots"),
            # flight-recorder health (runtime/blackbox.py), ridden in
            # on heartbeats: ring depth and seconds since the last
            # dump (-1 = never dumped); absent when the recorder is
            # off — consumers render "-"
            "blackbox_ring_depth": h.gauges.get("blackbox_ring_depth"),
            "blackbox_last_dump_age_s":
                h.gauges.get("blackbox_last_dump_age_s"),
            "counters": dict(h.counters),
        }
        if series:
            out["series"] = [list(x) for x in h.series][-32:]
        return out

    def _stages_locked(self, totals: dict | None) -> dict:
        """Per-stage measured stats (the kind=perf plane rolled up
        fleet-wide): client count, compute-rate and step-wall p50/p95
        from the direct reporters' latest snapshots merged with the
        digests' per-stage sketches — what the scheduler's cut
        re-planner reads instead of mirroring stage-1 profiles."""
        from split_learning_tpu.runtime import sketch
        stages: dict[str, dict] = {}
        for h in self._clients.values():
            if h.kind != "client" or h.stage is None \
                    or h.via is not None:
                continue
            ent = stages.setdefault(str(h.stage), {
                "n": 0, "crate": sketch.ValueSketch(),
                "step_ms": sketch.ValueSketch()})
            ent["n"] += 1
            ent["crate"].observe(h.gauges.get("compute_samples_per_s"))
            step = (h.latency.get("step_device")
                    or h.latency.get("step") or {})
            ent["step_ms"].observe(step.get("p95_ms"))
        for st, sd in ((totals or {}).get("stages") or {}).items():
            ent = stages.setdefault(str(st), {
                "n": 0, "crate": sketch.ValueSketch(),
                "step_ms": sketch.ValueSketch()})
            ent["n"] += int(sd.get("n", 0))
            ent["crate"].merge(sd.get("crate"))
            ent["step_ms"].merge(sd.get("step_ms"))
        out = {}
        for st, ent in sorted(stages.items(), key=lambda kv: kv[0]):
            crate, step_ms = ent["crate"], ent["step_ms"]
            out[st] = {
                "n": ent["n"],
                "compute_samples_per_s_p50": crate.quantile(50),
                "compute_samples_per_s_p95": crate.quantile(95),
                "step_p95_ms_p50": step_ms.quantile(50),
                "step_p95_ms_p95": step_ms.quantile(95),
            }
        return out

    def snapshot(self, now: float | None = None, *,
                 series: bool = True, page: int | None = None,
                 per_page: int = 256,
                 client: str | None = None) -> dict:
        """The ``/fleet`` JSON view (also the ``kind=fleet`` metrics
        record): per-client state/rate/score/age + the latest
        counter/wire snapshots each heartbeat flushed (so a client
        that crashes mid-round loses at most one interval of
        counters), recent transitions, and state counts.

        Under the digest roll-up the per-client block holds only the
        EXACT population (direct reporters + the bounded watchlist);
        everyone else is summarized in the ``digest`` block (exact
        counts/counter sums, quantile sketches, per-node summary).
        ``series=False`` drops the ring-buffer series (the summary
        shape); ``page`` (0-based, ``per_page`` ids per page) pages
        the per-client block; ``client`` restricts it to one id."""
        from split_learning_tpu.runtime import sketch
        now = time.time() if now is None else now
        with self._lock:
            ids = sorted(self._clients)
            total_ids = len(ids)
            if client is not None:
                ids = [c for c in ids if c == client]
            elif page is not None:
                per_page = max(1, int(per_page))
                ids = ids[page * per_page:(page + 1) * per_page]
            clients = {cid: self._view_of(cid, self._clients[cid],
                                          now, series)
                       for cid in ids}
            counts = self._counts_locked()
            totals = (sketch.merge_digests(
                [self._digests[n] for n in sorted(self._digests)],
                k=self.DIGEST_TOP_K) if self._digests else None)
            out = {
                "t": round(now, 3),
                "heartbeat_interval_s": self.interval,
                "liveness_timeout_s": self.liveness_timeout,
                "counts": {s: counts.get(s, 0) for s in HEALTH_STATES},
                "clients": clients,
                "transitions": list(self.transitions)[-64:],
            }
            stages = self._stages_locked(totals)
            if stages:
                out["stages"] = stages
            if page is not None or client is not None:
                out["paging"] = {
                    "page": page, "per_page": per_page,
                    "tracked_clients": total_ids,
                    "pages": -(-total_ids // max(1, per_page))}
            if totals is not None:
                # worst entries carry full views on the wire (watchlist
                # seeding); the JSON summary only needs the ranking
                out["digest"] = {
                    "nodes": {
                        nid: {"t": d.get("t"), "seq": d.get("seq"),
                              "clients": d.get("clients"),
                              "states": d.get("states")}
                        for nid, d in sorted(self._digests.items())},
                    "clients": totals.get("clients", 0),
                    "states": totals.get("states"),
                    "counters": totals.get("counters"),
                    "samples": totals.get("samples"),
                    "quantiles": sketch.digest_quantiles(totals),
                    "worst": [{k: w.get(k) for k in
                               ("client", "state", "score")}
                              for w in totals.get("worst") or []],
                }
                out["watchlist"] = sorted(
                    cid for cid, h in self._clients.items()
                    if h.via is not None)
            return out


# --------------------------------------------------------------------------
# Prometheus text-format exposition
# --------------------------------------------------------------------------

def _esc(v: Any) -> str:
    """Escape one label value per the text-format spec."""
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


#: perf-plane gauge -> dedicated /metrics family (runtime/perf.py):
#: (gauge name, metric name, type, help)
_PERF_FAMILIES = (
    ("mfu", "sl_mfu", "gauge",
     "Model-FLOPs utilization vs the datasheet bf16 peak."),
    ("step_seconds", "sl_step_seconds", "gauge",
     "Wall seconds of the last sampled (device-fenced) step."),
    ("hbm_peak_bytes", "sl_hbm_peak_bytes", "gauge",
     "Peak device memory bytes observed this round."),
    ("compile_seconds_total", "sl_compile_seconds_total", "counter",
     "Cumulative XLA compile wall-clock seconds."),
    # streaming aggregation plane (runtime/aggregate.py): host bytes
    # pinned by the delta codec's per-client shadow trees — what the
    # fleet-monitor `lost` prune and the elastic prune reclaim
    ("agg_shadow_bytes", "sl_agg_shadow_bytes", "gauge",
     "Host bytes pinned by per-client delta-codec shadow trees."),
)


def _sample(name: str, labels: dict, value: Any) -> str:
    if labels:
        body = ",".join(f'{k}="{_esc(v)}"'
                        for k, v in sorted(labels.items()))
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


def _series_order_key(item: tuple) -> tuple:
    """Cap ordering for per-client /metrics series, worst first:
    watchlist members (digest-sourced exact views) before direct
    reporters, then state severity, then straggler score, then id —
    so a bounded scrape always shows the clients that need looking
    at."""
    cid, c = item
    score = c.get("straggler_score")
    return (0 if c.get("via") else 1,
            -_STATE_CODE.get(c.get("state", "healthy"), 0),
            score if score is not None else math.inf,
            cid)


def render_prometheus(fleet: FleetMonitor | None = None, faults=None,
                      wire=None, hists=None,
                      gauges: GaugeSet | None = None,
                      max_client_series: int | None = None) -> str:
    """One ``/metrics`` page: process counters/gauges/latency digests
    plus the per-client fleet view.  Pure string building — safe to
    call from the exporter's HTTP threads mid-round.

    ``max_client_series`` bounds the per-client ``sl_client_*``
    cardinality (``observability.max-client-series``): when the exact
    population exceeds it, the watchlist/worst clients render first
    (:func:`_series_order_key`) and the rest are summarized by the
    fleet-level families (``sl_fleet_clients``,
    ``sl_fleet_rate_quantile``) — a 100k-client scrape stays the size
    of a 256-client one."""
    out: list[str] = []

    def family(name: str, kind: str, help_: str, samples: list):
        if not samples:
            return
        out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} {kind}")
        out.extend(samples)

    if faults is not None:
        fsnap = faults.snapshot()
        family("sl_faults_total", "counter",
               "Cumulative fault/recovery counters (runtime/trace.py).",
               [_sample("sl_faults_total", {"name": k}, v)
                for k, v in sorted(fsnap.items())])
        family("sl_retraces_total", "counter",
               "Compiles observed after round 0 (runtime/perf.py "
               "CompileWatch — the live JX004 retrace rule).",
               [_sample("sl_retraces_total", {},
                        fsnap.get("retraces", 0))])
    if wire is not None:
        w = wire.snapshot()
        family("sl_wire_bytes_total", "counter",
               "Cumulative wire bytes by direction.",
               [_sample("sl_wire_bytes_total", {"direction": "out"},
                        w.get("bytes_out_total", 0)),
                _sample("sl_wire_bytes_total", {"direction": "in"},
                        w.get("bytes_in_total", 0))])
        family("sl_wire_messages_total", "counter",
               "Cumulative wire messages by direction.",
               [_sample("sl_wire_messages_total", {"direction": "out"},
                        w.get("msgs_out", 0)),
                _sample("sl_wire_messages_total", {"direction": "in"},
                        w.get("msgs_in", 0))])
    if gauges is not None:
        gsnap = gauges.snapshot()
        family("sl_gauge", "gauge",
               "Last-value gauges (runtime/trace.py GAUGE_NAMES).",
               [_sample("sl_gauge", {"name": k}, v)
                for k, v in sorted(gsnap.items())
                if k in GAUGE_NAMES and _finite(v)])
        # perf-plane gauges additionally published under dedicated
        # names (runtime/perf.py; the compute half of the compute/wire
        # ratio the scheduler consumes)
        for gname, mname, kind, help_ in _PERF_FAMILIES:
            v = gsnap.get(gname)
            if v is not None and _finite(v):
                family(mname, kind, help_, [_sample(mname, {}, v)])
    if hists is not None:
        h = hists.snapshot()
        q_samples, n_samples = [], []
        for name, digest in sorted(h.items()):
            for q, key in (("0.5", "p50_ms"), ("0.95", "p95_ms"),
                           ("0.99", "p99_ms")):
                ms = digest.get(key)
                if ms is not None:
                    q_samples.append(_sample(
                        "sl_latency_seconds",
                        {"name": name, "quantile": q}, ms / 1e3))
            n_samples.append(_sample("sl_latency_observations_total",
                                     {"name": name},
                                     digest.get("count", 0)))
        family("sl_latency_seconds", "summary",
               "Latency digests (log-spaced fixed buckets).", q_samples)
        family("sl_latency_observations_total", "counter",
               "Observations per latency histogram.", n_samples)
    if fleet is not None:
        snap = fleet.snapshot(series=False)
        by_state = [_sample("sl_fleet_clients", {"state": s}, n)
                    for s, n in sorted(snap["counts"].items())]
        family("sl_fleet_clients", "gauge",
               "Clients per health state (exact under the digest "
               "roll-up).", by_state)
        dig = snap.get("digest")
        if dig:
            family("sl_fleet_digest_nodes", "gauge",
                   "Aggregator nodes reporting FleetDigest roll-ups.",
                   [_sample("sl_fleet_digest_nodes", {},
                            len(dig.get("nodes") or {}))])
            family("sl_fleet_digest_clients", "gauge",
                   "Clients covered by digest roll-ups (exact state "
                   "lives on their aggregator node).",
                   [_sample("sl_fleet_digest_clients", {},
                            dig.get("clients", 0))])
            q_samples = []
            for key, v in sorted((dig.get("quantiles")
                                  or {}).items()):
                field, _, q = key.rpartition("_p")
                name = ("sl_fleet_rate_quantile"
                        if field == "rate"
                        else "sl_fleet_compute_rate_quantile")
                if _finite(v):
                    q_samples.append((name,
                                      _sample(name,
                                              {"quantile":
                                               f"0.{q}"}, v)))
            for name, help_ in (
                    ("sl_fleet_rate_quantile",
                     "Fleet-wide samples/s quantiles from the merged "
                     "digest sketch (error <= one 2^0.25 bucket)."),
                    ("sl_fleet_compute_rate_quantile",
                     "Fleet-wide device-rate quantiles from the "
                     "merged digest sketch.")):
                family(name, "gauge", help_,
                       [s for n, s in q_samples if n == name])
        items = sorted(snap["clients"].items())
        if max_client_series is not None \
                and len(items) > max_client_series:
            capped = sorted(items, key=_series_order_key)
            items = sorted(capped[:max_client_series])
        family("sl_fleet_client_series", "gauge",
               "Per-client series rendered below (bounded by "
               "observability.max-client-series; the rest live in "
               "the fleet-level families).",
               [_sample("sl_fleet_client_series", {}, len(items))])
        up, code, rate, score, age = [], [], [], [], []
        mfu, crate, vlag = [], [], []
        for cid, c in items:
            lbl = {"client": cid}
            up.append(_sample("sl_client_up", lbl,
                              0 if c["state"] == "lost" else 1))
            code.append(_sample("sl_client_state_code", lbl,
                                _STATE_CODE[c["state"]]))
            if c["samples_per_s"] is not None:
                rate.append(_sample("sl_client_samples_per_second",
                                    lbl, c["samples_per_s"]))
            if c["straggler_score"] is not None:
                score.append(_sample("sl_client_straggler_score", lbl,
                                     c["straggler_score"]))
            if c.get("version_lag") is not None:
                vlag.append(_sample("sl_client_version_lag", lbl,
                                    c["version_lag"]))
            if c.get("mfu") is not None:
                mfu.append(_sample("sl_client_mfu", lbl, c["mfu"]))
            if c.get("compute_samples_per_s") is not None:
                crate.append(_sample(
                    "sl_client_compute_samples_per_second", lbl,
                    c["compute_samples_per_s"]))
            age.append(_sample("sl_client_heartbeat_age_seconds", lbl,
                               c["age_s"]))
        family("sl_client_up", "gauge",
               "1 unless the client is health-state lost.", up)
        family("sl_client_state_code", "gauge",
               "0=healthy 1=degraded 2=straggler 3=lost.", code)
        family("sl_client_samples_per_second", "gauge",
               "EWMA training throughput per client.", rate)
        family("sl_client_straggler_score", "gauge",
               "Client rate / fleet median (lower is slower).", score)
        family("sl_client_version_lag", "gauge",
               "Versions behind the server's current cut "
               "(async bounded-staleness mode).", vlag)
        family("sl_client_mfu", "gauge",
               "Per-client model-FLOPs utilization (perf plane).", mfu)
        family("sl_client_compute_samples_per_second", "gauge",
               "Per-client samples/s over device-busy time.", crate)
        family("sl_client_heartbeat_age_seconds", "gauge",
               "Seconds since the last fresh frame.", age)
    return "\n".join(out) + ("\n" if out else "")


_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_TYPES = ("counter", "gauge", "summary", "histogram", "untyped")


def _finite(v) -> bool:
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError):
        return False


def _parse_labels(body: str) -> dict | None:
    """Parse ``k="v",...`` with escape handling; None on bad syntax."""
    labels: dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        j = body.find("=", i)
        if j < 0:
            return None
        name = body[i:j]
        if not _LABEL_NAME_RE.match(name):
            return None
        if j + 1 >= n or body[j + 1] != '"':
            return None
        i = j + 2
        val = []
        while i < n and body[i] != '"':
            if body[i] == "\\":
                if i + 1 >= n or body[i + 1] not in ('\\', '"', 'n'):
                    return None
                val.append(body[i:i + 2])
                i += 2
            else:
                val.append(body[i])
                i += 1
        if i >= n:            # unterminated value
            return None
        i += 1                # closing quote
        if name in labels:
            return None       # duplicate label name
        labels[name] = "".join(val)
        if i < n:
            if body[i] != ",":
                return None
            i += 1
    return labels


def lint_prometheus(text: str) -> list[str]:
    """Pure-python Prometheus text-format lint: metric/label name
    grammar, label-value escaping, float-parseable values, TYPE
    declared before a family's first sample, no duplicate series.
    Returns a list of errors (empty = parseable)."""
    errors: list[str] = []
    typed: set[str] = set()
    seen: set[tuple] = set()
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                if not _METRIC_NAME_RE.match(parts[2]):
                    errors.append(f"line {lineno}: bad metric name "
                                  f"{parts[2]!r} in {parts[1]}")
                if parts[1] == "TYPE":
                    if len(parts) < 4 or parts[3] not in _TYPES:
                        errors.append(f"line {lineno}: bad TYPE "
                                      f"{line!r}")
                    typed.add(parts[2])
            continue
        m = re.match(r"^([^\s{]+)(\{(.*)\})?\s+(\S+)(\s+-?\d+)?$", line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name, _, label_body, value = m.group(1, 2, 3, 4)
        if not _METRIC_NAME_RE.match(name):
            errors.append(f"line {lineno}: bad metric name {name!r}")
            continue
        labels = _parse_labels(label_body) if label_body else {}
        if labels is None:
            errors.append(f"line {lineno}: bad label syntax "
                          f"{label_body!r}")
            continue
        if value not in ("NaN", "+Inf", "-Inf"):
            try:
                float(value)
            except ValueError:
                errors.append(f"line {lineno}: unparseable value "
                              f"{value!r}")
        base = re.sub(r"_(count|sum|bucket)$", "", name)
        if name not in typed and base not in typed:
            errors.append(f"line {lineno}: sample {name!r} has no "
                          "preceding # TYPE")
        key = (name, tuple(sorted(labels.items())))
        if key in seen:
            errors.append(f"line {lineno}: duplicate series {key}")
        seen.add(key)
    return errors


# --------------------------------------------------------------------------
# HTTP exporter
# --------------------------------------------------------------------------

class TelemetryExporter:
    """Stdlib HTTP thread serving ``/metrics`` (Prometheus text,
    ``text/plain; version=0.0.4``) and ``/fleet`` (JSON snapshot),
    plus ``POST /profile?steps=K`` when a ``profile_fn`` is wired
    (arms the perf plane's on-demand ``jax.profiler`` capture,
    ``runtime/perf.py ProfileCapture.arm``).  Callbacks run on the
    handler threads — keep them lock-cheap (the FleetMonitor/
    registries are all internally locked; ``arm`` only flips state)."""

    def __init__(self, metrics_fn: Callable[[], str],
                 fleet_fn: Callable[[], dict],
                 host: str = "127.0.0.1", port: int = 0,
                 profile_fn: Callable[[int], dict] | None = None):
        import http.server

        exporter = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API
                try:
                    path, _, query = self.path.partition("?")
                    if path == "/metrics":
                        body = exporter._metrics_fn().encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path == "/fleet":
                        if exporter._fleet_wants_query:
                            import urllib.parse
                            q = {k: v[-1] for k, v in
                                 urllib.parse.parse_qs(query).items()}
                            snap = exporter._fleet_fn(q)
                        else:
                            snap = exporter._fleet_fn()
                        body = json.dumps(snap).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # noqa: BLE001 — a render bug
                    # must 500 the scrape, not kill the handler thread
                    self.send_error(500, str(e)[:100])
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802 — stdlib API
                path, _, query = self.path.partition("?")
                if path != "/profile" or exporter._profile_fn is None:
                    self.send_error(404)
                    return
                try:
                    import urllib.parse
                    q = urllib.parse.parse_qs(query)
                    steps = int(q.get("steps", ["1"])[0])
                    body = json.dumps(
                        exporter._profile_fn(steps)).encode()
                except (ValueError, TypeError):
                    self.send_error(400, "steps must be an integer")
                    return
                except Exception as e:  # noqa: BLE001 — see do_GET
                    self.send_error(500, str(e)[:100])
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # scrapes must not spam stderr
                pass

        self._metrics_fn = metrics_fn
        self._fleet_fn = fleet_fn
        # a fleet_fn taking a parameter receives the query-string dict
        # (?full=1 / ?page=N / ?client=id — the summary-mode knobs);
        # zero-arg callables (tests, old callers) keep working
        import inspect
        try:
            self._fleet_wants_query = bool(
                inspect.signature(fleet_fn).parameters)
        except (TypeError, ValueError):
            self._fleet_wants_query = False
        self._profile_fn = profile_fn
        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "TelemetryExporter":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"telemetry-http-{self.port}")
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
