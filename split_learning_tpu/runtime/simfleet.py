"""In-process synthetic-client fleet: the scheduler's proof rig and
the control-plane load generator.

Driving 1k–10k REAL jax clients through a round is not possible on one
host — but the scheduler, the telemetry plane, the aggregation plane
and the registration/barrier machinery never see a client's jax; they
see its *frames*.  This module provides clients that speak exactly the
wire protocol (REGISTER → READY → NOTIFY → UPDATE, heartbeats with
telemetry snapshots, STOP handling) against the real
:class:`~split_learning_tpu.runtime.server.ProtocolServer` over a
shared in-proc transport, while their "training" is a timed event:
each client has a configured compute speed (samples/s) and wire
bandwidth (bytes/s), finishes its round after
``(samples/compute + update_bytes/wire) * time_scale`` seconds, and
reports honest telemetry about those rates.  One driver thread
multiplexes the whole fleet off an event heap, so 10k clients cost 10k
queue polls per sweep, not 10k threads.

What this substrate exercises for real:

* registration storms and the per-stage registration barrier;
* the rpc pump, heartbeat ingestion and the FleetMonitor state
  machine at fleet scale;
* the full START/READY/SYN/NOTIFY/PAUSE/UPDATE choreography and the
  streaming aggregation fold (clients echo their START shard back, so
  the fold is a real per-stage weighted fold over real TENSOR frames);
* the closed-loop scheduler: sim clients honor the per-client knob
  frames (a granted codec retune shrinks their simulated wire time by
  ``codec_gain``), get demoted/evicted/barrier-dropped like real
  clients, and membership churn (timed joins/leaves) drives the
  elastic re-plan path.

Used by ``tools/sl_fleet_sim.py`` (CLI), the ``sched_fleet`` bench
cell and the ``run_chaos.py --sched`` CI cell.
"""

from __future__ import annotations

import dataclasses
import heapq
import threading
import time

import numpy as np

from split_learning_tpu.runtime.bus import shard_for
from split_learning_tpu.runtime.protocol import (
    DigestRoute, FrameAssembler, Heartbeat, Notify, Pause, Ready,
    Register, Start, Stop, Syn, Update, encode, reply_queue, RPC_QUEUE,
)


@dataclasses.dataclass
class SimClientSpec:
    """One synthetic client's resource envelope."""
    cid: str
    stage: int = 1
    compute_speed: float = 100.0     # device samples/s
    wire_bytes_per_s: float = 0.0    # 0 = unconstrained wire
    samples: int = 32                # samples contributed per round
    join_delay_s: float = 0.0        # churn: register this late
    leave_after_rounds: int | None = None   # churn: go silent after N
    profile: dict | None = None      # REGISTER profile


def hetero_fleet(n_stage1: int, n_heads: int = 1, *,
                 compute_speed: float = 100.0,
                 compute_slow: int = 0, compute_slow_factor: float = 8.0,
                 wire_slow: int = 0, wire_slow_bytes_per_s: float = 0.0,
                 samples: int = 32, n_layers: int = 4,
                 update_bytes: float = 64 << 10,
                 joiners: int = 0, join_delay_s: float = 0.0,
                 leavers: int = 0, leave_after_rounds: int = 1,
                 seed: int = 0) -> list[SimClientSpec]:
    """A heterogeneous fleet: mostly-uniform healthy clients plus
    ``compute_slow`` clients at ``compute_speed/compute_slow_factor``
    and ``wire_slow`` clients whose wire drains at
    ``wire_slow_bytes_per_s`` (default: slow enough that wire time
    ~= 6x compute time).  The first ``joiners`` healthy clients
    register ``join_delay_s`` late; the last ``leavers`` go silent
    after ``leave_after_rounds`` rounds.  Deterministic per seed."""
    rng = np.random.default_rng(seed)
    if not wire_slow_bytes_per_s:
        wire_slow_bytes_per_s = update_bytes \
            / (6.0 * samples / compute_speed)
    specs: list[SimClientSpec] = []
    n_slow = min(compute_slow, n_stage1)
    n_wslow = min(wire_slow, max(0, n_stage1 - n_slow))
    for i in range(n_stage1):
        cid = f"sim_1_{i:05d}"
        speed = float(compute_speed * rng.uniform(0.9, 1.1))
        wire = 0.0
        if i < n_slow:
            speed = compute_speed / compute_slow_factor
        elif i < n_slow + n_wslow:
            wire = wire_slow_bytes_per_s
        per_layer = (1.0 / speed) / n_layers
        specs.append(SimClientSpec(
            cid=cid, stage=1, compute_speed=speed,
            wire_bytes_per_s=wire, samples=samples,
            join_delay_s=(join_delay_s
                          if n_slow + n_wslow <= i
                          < n_slow + n_wslow + joiners else 0.0),
            leave_after_rounds=(leave_after_rounds
                                if i >= n_stage1 - leavers else None),
            profile={"exe_time": [per_layer] * n_layers,
                     "size_data": [float(update_bytes)] * n_layers,
                     "speed": speed, "network": 0.0}))
    for i in range(n_heads):
        specs.append(SimClientSpec(
            cid=f"sim_2_{i:05d}", stage=2,
            compute_speed=float(compute_speed), samples=samples))
    return specs


class _SimClient:
    """Driver-side state for one synthetic client."""

    def __init__(self, spec: SimClientSpec):
        self.spec = spec
        self.asm = FrameAssembler()
        self.registered = False
        self.started = False         # first START seen
        self.stopped = False
        self.params = None           # echo of the last START shard
        self.stats = None
        self.cluster = 0
        self.fence = 0
        self.round_idx = 0
        self.rounds_done = 0
        self.finish_t = 0.0          # wall time this round completes
        self.paused = False          # PAUSE seen, UPDATE owed
        self.send_weights = True
        self.codec_gain = 1.0        # scheduler knob: wire divider
        self.hb_queue = None         # digest roll-up heartbeat target
        self.seq = 0
        self.total_samples = 0


class _FleetDriver:
    """One driver thread's slice of the fleet: its own transport
    (so sim traffic fans out over real per-shard broker connections
    instead of multiplexing every client through one socket), its own
    event heap, its own poll sweep."""

    def __init__(self, bus, owns_bus: bool):
        self.bus = bus
        self.owns_bus = owns_bus
        self.clients: dict[str, _SimClient] = {}
        self.events: list = []       # (t, seq, kind, cid)
        self.eseq = 0
        self.thread: threading.Thread | None = None


class SyntheticFleet:
    """Event-driven synthetic fleet over a shared transport.

    ``start()`` launches the driver thread(s); clients with
    ``join_delay_s == 0`` REGISTER immediately in one burst (the
    registration-storm shape), the rest on their timers.  ``stop()``
    (or a server STOP fan-out) winds it down.  ``time_scale``
    multiplies every simulated duration — 1.0 for wall-realistic
    cells, small values to make a 10k-client round cheap.

    **Sharded broker planes** (``broker.shards``): pass ``drivers > 1``
    plus a ``bus_factory`` and the fleet partitions its clients across
    that many driver threads, each owning a fresh factory-built
    transport — clients land on the driver that owns their reply
    queue's SHARD (``shard_for``), so the sim's publishes and polls
    exercise the real multi-shard fan-out instead of funneling 10k
    clients through one broker connection.  The default (one driver,
    the shared ``bus``) is the classic in-proc shape, unchanged."""

    POLL_BATCH = 4        # frames consumed per client per sweep
    REREGISTER_S = 1.0    # REGISTER retry period until first START

    def __init__(self, bus, specs: list[SimClientSpec], *,
                 heartbeat_interval: float = 0.5,
                 time_scale: float = 1.0,
                 update_bytes: float = 64 << 10,
                 codec_gain: float = 4.0,
                 drivers: int = 1, bus_factory=None):
        self.bus = bus
        self.heartbeat_interval = float(heartbeat_interval)
        self.time_scale = float(time_scale)
        self.update_bytes = float(update_bytes)
        self.codec_gain = float(codec_gain)
        self.clients = {s.cid: _SimClient(s) for s in specs}
        drivers = max(1, int(drivers))
        # with a factory every driver owns a fresh transport (the
        # per-shard fan-out); without one they share `bus` (the
        # classic in-proc cell — drivers then only parallelize sweeps)
        self._drivers = [
            _FleetDriver(bus_factory(), owns_bus=True)
            if bus_factory is not None else
            _FleetDriver(bus, owns_bus=False)
            for _ in range(drivers)]
        shards = int(getattr(self._drivers[0].bus, "shards", 1) or 1)
        for i, (cid, c) in enumerate(sorted(self.clients.items())):
            if shards > 1:
                # shard-affine placement: a driver polls queues that
                # live on (mostly) one shard, so sweeps ride that
                # shard's connection instead of ping-ponging
                d = shard_for(reply_queue(cid), shards) % drivers
            else:
                d = i % drivers
            self._drivers[d].clients[cid] = c
        self._stop = threading.Event()
        self.errors: list[str] = []

    # -- timing model --------------------------------------------------------

    def _durations(self, c: _SimClient) -> tuple[float, float]:
        """(compute_s, wire_s) of one round in UNSCALED time — the
        rates the client reports; the event heap uses the scaled sum."""
        sp = c.spec
        compute_t = sp.samples / max(sp.compute_speed, 1e-9)
        wire_t = 0.0
        if sp.wire_bytes_per_s > 0:
            wire_t = (self.update_bytes
                      / (sp.wire_bytes_per_s * c.codec_gain))
        return compute_t, wire_t

    def _telemetry(self, c: _SimClient) -> dict:
        compute_t, wire_t = self._durations(c)
        rate = c.spec.samples / (compute_t + wire_t)
        c.seq += 1
        return {
            "part": c.spec.cid, "t": time.time(), "seq": c.seq,
            "kind": "client", "stage": c.spec.stage,
            "round": c.round_idx,
            "samples": c.total_samples,
            "samples_per_s": round(rate, 3),
            "gauges": {"samples_per_s": round(rate, 3),
                       "compute_samples_per_s":
                           round(c.spec.compute_speed, 3)},
            "counters": {}, "wire": {},
            # honest per-stage step wall: the configured compute time
            # per sample in ms — what the digest path's per-stage
            # stats and the cut re-planner consume
            "latency": {"step_device": {
                "p95_ms": round(1e3 / max(c.spec.compute_speed,
                                          1e-9), 4)}},
            "v": 1,
        }

    # -- wire actions --------------------------------------------------------

    def _register(self, d: _FleetDriver, c: _SimClient) -> None:
        d.bus.publish(RPC_QUEUE, encode(Register(
            client_id=c.spec.cid, stage=c.spec.stage,
            profile=c.spec.profile)))
        c.registered = True

    def _beat(self, d: _FleetDriver, c: _SimClient) -> None:
        d.bus.publish(c.hb_queue or RPC_QUEUE, encode(Heartbeat(
            client_id=c.spec.cid, round_idx=c.round_idx,
            telemetry=self._telemetry(c))))

    def _send_update(self, d: _FleetDriver, c: _SimClient) -> None:
        d.bus.publish(RPC_QUEUE, encode(Update(
            client_id=c.spec.cid, stage=c.spec.stage,
            cluster=c.cluster,
            params=(c.params if c.send_weights else None),
            batch_stats=(c.stats if c.send_weights else None),
            num_samples=c.spec.samples, ok=True,
            round_idx=c.fence, telemetry=self._telemetry(c))))
        c.paused = False
        c.rounds_done += 1
        c.total_samples += c.spec.samples
        lv = c.spec.leave_after_rounds
        if lv is not None and c.rounds_done >= lv:
            c.stopped = True   # churn: silent from here on

    # -- event plumbing ------------------------------------------------------

    @staticmethod
    def _at(d: _FleetDriver, t: float, kind: str, cid: str) -> None:
        d.eseq += 1
        heapq.heappush(d.events, (t, d.eseq, kind, cid))

    def _handle(self, d: _FleetDriver, c: _SimClient, msg) -> None:
        now = time.monotonic()
        if isinstance(msg, Start):
            extra = msg.extra or {}
            c.started = True
            c.cluster = msg.cluster
            c.round_idx = msg.round_idx
            c.fence = int(extra.get("gen", msg.round_idx))
            if msg.params is not None:
                c.params = msg.params
                c.stats = msg.batch_stats
            knobs = extra.get("sched") or {}
            c.codec_gain = (self.codec_gain
                            if knobs.get("codec") else 1.0)
            c.hb_queue = extra.get("digest")
            d.bus.publish(RPC_QUEUE, encode(Ready(
                client_id=c.spec.cid, round_idx=c.fence)))
        elif isinstance(msg, Syn):
            compute_t, wire_t = self._durations(c)
            c.finish_t = now + (compute_t + wire_t) * self.time_scale
            if c.spec.stage == 1:
                self._at(d, c.finish_t, "notify", c.spec.cid)
        elif isinstance(msg, Pause):
            c.paused = True
            c.send_weights = bool(msg.send_weights)
            if now >= c.finish_t:
                self._send_update(d, c)
            else:
                self._at(d, c.finish_t, "update", c.spec.cid)
        elif isinstance(msg, DigestRoute):
            # digest-node death fallback: adopt the new heartbeat
            # target and beat once immediately (a real client does the
            # same) so the server's liveness view never gaps
            c.hb_queue = msg.queue
            self._beat(d, c)
        elif isinstance(msg, Stop):
            c.stopped = True

    def _fire(self, d: _FleetDriver, kind: str, c: _SimClient) -> None:
        if c.stopped:
            return
        if kind == "join":
            self._register(d, c)
            if self.heartbeat_interval > 0:
                self._at(d, time.monotonic() + self.heartbeat_interval,
                         "beat", c.spec.cid)
            self._at(d, time.monotonic() + self.REREGISTER_S,
                     "reregister", c.spec.cid)
        elif kind == "reregister":
            # like a real client: REGISTER is re-sent until the first
            # START lands, so the server's startup queue purge (or a
            # dropped frame) cannot lose this client forever
            if not c.started:
                self._register(d, c)
                self._at(d, time.monotonic() + self.REREGISTER_S,
                         "reregister", c.spec.cid)
        elif kind == "beat":
            if self.heartbeat_interval > 0:
                self._beat(d, c)
                self._at(d, time.monotonic() + self.heartbeat_interval,
                         "beat", c.spec.cid)
        elif kind == "notify":
            d.bus.publish(RPC_QUEUE, encode(Notify(
                client_id=c.spec.cid, cluster=c.cluster,
                round_idx=c.fence)))
        elif kind == "update":
            if c.paused:
                self._send_update(d, c)

    # -- driver loop ---------------------------------------------------------

    def _run(self, d: _FleetDriver) -> None:
        now = time.monotonic()
        for c in d.clients.values():
            if c.spec.join_delay_s > 0:
                self._at(d, now + c.spec.join_delay_s, "join",
                         c.spec.cid)
            else:
                self._register(d, c)   # the registration-storm burst
                if self.heartbeat_interval > 0:
                    self._at(d, now + self.heartbeat_interval, "beat",
                             c.spec.cid)
                self._at(d, now + self.REREGISTER_S, "reregister",
                         c.spec.cid)
        while not self._stop.is_set():
            busy = False
            now = time.monotonic()
            while d.events and d.events[0][0] <= now:
                _, _, kind, cid = heapq.heappop(d.events)
                self._fire(d, kind, d.clients[cid])
                busy = True
            # InProcTransport fast path: peek queue lengths WITHOUT
            # taking the bus lock (a CPython len() read is atomic and
            # at worst one sweep stale).  A locked get() per client
            # per sweep is 10k lock acquisitions contending with the
            # server's fan-out publishes — the difference between an
            # 82/s and a >1k/s START drain at 10k clients.  (Over a
            # sharded TCP plane there is nothing to peek: each poll is
            # a real zero-timeout GET routed to the owning shard.)
            peek = getattr(d.bus, "_queues", None)
            for c in d.clients.values():
                if c.stopped or not c.registered:
                    continue
                q = reply_queue(c.spec.cid)
                if peek is not None and not peek.get(q):
                    continue
                for _ in range(self.POLL_BATCH):
                    try:
                        raw = d.bus.get(q, timeout=0)
                    except Exception:  # noqa: BLE001 — bus closed:
                        return         # the deployment is over
                    if raw is None:
                        break
                    busy = True
                    try:
                        msg = c.asm.feed(raw)
                    except Exception as e:  # noqa: BLE001 — corrupt
                        self.errors.append(f"{c.spec.cid}: {e}")
                        continue
                    if msg is not None:
                        self._handle(d, c, msg)
            if not busy:
                # idle: sleep to the next event (bounded) instead of
                # spinning the poll sweep
                wake = (d.events[0][0] - time.monotonic()
                        if d.events else 0.005)
                self._stop.wait(min(max(wake, 0.0005), 0.02))

    def start(self) -> "SyntheticFleet":
        for i, d in enumerate(self._drivers):
            d.thread = threading.Thread(
                target=self._run, args=(d,), daemon=True,
                name=f"simfleet-driver-{i}")
            d.thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for d in self._drivers:
            if d.thread is not None:
                d.thread.join(timeout=10.0)
                d.thread = None
            if d.owns_bus:
                try:
                    d.bus.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
