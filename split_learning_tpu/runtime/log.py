"""Structured logging with console mirroring.

Parity with the reference logger (``/root/reference/src/Log.py``): a file
logger writing timestamped records to ``{log_path}/app.log``, mirrored to
the console with ANSI colors, with ``[>>>]``/``[<<<]`` direction markers
for protocol messages and a ``debug_mode`` gate.  Additions: per-round
structured metrics records (JSON lines in ``metrics.jsonl``) so runs are
machine-readable, which the reference lacks (SURVEY.md §5.5).

Console mirroring runs THROUGH the logging stack (a color-formatting
``StreamHandler`` attached only when ``console=True``) rather than raw
``print_with_color`` calls next to it: every console line shares the
file record's timestamp (so console output lines up with app.log and
the span journals), and the ``console=False`` gate is structural — no
code path can print around it.

``metrics.jsonl`` is append-only across runs; every record is stamped
with a run-scoped :data:`RUN_ID`, the writing ``participant`` and an
explicit ``kind`` (default ``round``) so interleaved runs separate
cleanly, and each line is flushed as written so a crashed run keeps its
tail.
"""

from __future__ import annotations

import json
import logging
import pathlib
import sys
import threading
import time
import uuid

_COLORS = {
    "red": "\033[91m", "green": "\033[92m", "yellow": "\033[93m",
    "blue": "\033[94m", "magenta": "\033[95m", "cyan": "\033[96m",
    "white": "\033[97m", "reset": "\033[0m",
}

#: run-scoped id: one per process, stamped on every metrics record (and
#: adoptable via ``Logger(run_id=...)`` when a driver coordinates
#: several processes of one logical run)
RUN_ID = uuid.uuid4().hex[:12]

_FMT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"

#: colors applied by level when the call site names none
_LEVEL_COLORS = {logging.WARNING: "yellow", logging.ERROR: "red",
                 logging.DEBUG: "cyan"}


def print_with_color(text: str, color: str = "white") -> None:
    """Raw colored stdout write (reference ``Log.py`` parity helper).
    Logger no longer routes console output here — its mirror runs
    through the :class:`_ColorFormatter` handler so every console line
    is timestamped and structurally gated by ``console=False``."""
    sys.stdout.write(f"{_COLORS.get(color, '')}{text}{_COLORS['reset']}\n")


class _ColorFormatter(logging.Formatter):
    """app.log format + ANSI color from ``extra={'color': ...}`` (or
    the level default), for the console mirror handler."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        color = getattr(record, "color", None) \
            or _LEVEL_COLORS.get(record.levelno)
        if color in _COLORS:
            return f"{_COLORS[color]}{base}{_COLORS['reset']}"
        return base


class Logger:
    """File + console logger with structured metrics sidecar."""

    def __init__(self, log_path: str | pathlib.Path = ".",
                 debug: bool = False, console: bool = True,
                 name: str = "split_learning_tpu",
                 run_id: str | None = None):
        self.debug_mode = debug
        self.console = console
        self.participant = name
        self.run_id = run_id or RUN_ID
        root = pathlib.Path(log_path)
        root.mkdir(parents=True, exist_ok=True)
        self._metrics_path = root / "metrics.jsonl"
        self._metrics_lock = threading.Lock()
        self._metrics_f = None
        self._log = logging.getLogger(f"{name}.{id(self):x}")
        self._log.setLevel(logging.DEBUG)
        self._log.propagate = False
        # id() values recycle: a reused registry entry may still carry a
        # previous Logger's handler — drop any stale ones
        for h in list(self._log.handlers):
            self._log.removeHandler(h)
            h.close()
        handler = logging.FileHandler(root / "app.log")
        # %(name)s carries the participant ("server"/"{client_id}"):
        # an in-process cell interleaves every participant in ONE
        # app.log, and the protocol-model trace validator
        # (analysis/model.py events_from_log) needs it to replay each
        # participant's state machine separately
        handler.setFormatter(logging.Formatter(_FMT))
        self._log.addHandler(handler)
        self._handler = handler
        self._console_handler = None
        if console:
            ch = logging.StreamHandler(sys.stdout)
            ch.setFormatter(_ColorFormatter(_FMT))
            self._log.addHandler(ch)
            self._console_handler = ch

    def _emit(self, level: int, msg: str, color: str | None = None):
        self._log.log(level, msg,
                      extra=None if color is None else {"color": color})

    def info(self, msg: str, color: str = "white") -> None:
        self._emit(logging.INFO, msg, color)

    def warning(self, msg: str) -> None:
        self._emit(logging.WARNING, msg)

    def error(self, msg: str) -> None:
        self._emit(logging.ERROR, msg)

    def debug(self, msg: str) -> None:
        if self.debug_mode:
            self._emit(logging.DEBUG, msg)

    def sent(self, msg: str) -> None:
        """Outbound protocol message (reference's red ``[>>>]`` marker)."""
        self.info(f"[>>>] {msg}", "red")

    def received(self, msg: str) -> None:
        """Inbound protocol message (reference's blue ``[<<<]`` marker)."""
        self.info(f"[<<<] {msg}", "blue")

    def metric(self, **fields) -> None:
        """Append one structured metrics record (JSON line), stamped
        ``run_id``/``participant``/``kind`` and flushed immediately so
        a crashed run keeps every completed record."""
        rec = {"ts": time.time(), "run_id": self.run_id,
               "participant": self.participant,
               "kind": fields.pop("kind", "round")}
        rec.update(fields)
        line = json.dumps(rec) + "\n"
        with self._metrics_lock:
            if self._metrics_f is None or self._metrics_f.closed:
                self._metrics_f = open(self._metrics_path, "a")
            self._metrics_f.write(line)
            self._metrics_f.flush()

    def close(self) -> None:
        self._handler.close()
        self._log.removeHandler(self._handler)
        if self._console_handler is not None:
            self._log.removeHandler(self._console_handler)
            self._console_handler = None
        with self._metrics_lock:
            if self._metrics_f is not None and not self._metrics_f.closed:
                self._metrics_f.close()
