"""Structured logging with console mirroring.

Parity with the reference logger (``/root/reference/src/Log.py``): a file
logger writing timestamped records to ``{log_path}/app.log``, mirrored to
the console with ANSI colors, with ``[>>>]``/``[<<<]`` direction markers
for protocol messages and a ``debug_mode`` gate.  Additions: per-round
structured metrics records (JSON lines in ``metrics.jsonl``) so runs are
machine-readable, which the reference lacks (SURVEY.md §5.5).
"""

from __future__ import annotations

import json
import logging
import pathlib
import sys
import time

_COLORS = {
    "red": "\033[91m", "green": "\033[92m", "yellow": "\033[93m",
    "blue": "\033[94m", "magenta": "\033[95m", "cyan": "\033[96m",
    "white": "\033[97m", "reset": "\033[0m",
}


def print_with_color(text: str, color: str = "white") -> None:
    sys.stdout.write(f"{_COLORS.get(color, '')}{text}{_COLORS['reset']}\n")


class Logger:
    """File + console logger with structured metrics sidecar."""

    def __init__(self, log_path: str | pathlib.Path = ".",
                 debug: bool = False, console: bool = True,
                 name: str = "split_learning_tpu"):
        self.debug_mode = debug
        self.console = console
        root = pathlib.Path(log_path)
        root.mkdir(parents=True, exist_ok=True)
        self._metrics_path = root / "metrics.jsonl"
        self._log = logging.getLogger(f"{name}.{id(self):x}")
        self._log.setLevel(logging.DEBUG)
        self._log.propagate = False
        # id() values recycle: a reused registry entry may still carry a
        # previous Logger's handler — drop any stale ones
        for h in list(self._log.handlers):
            self._log.removeHandler(h)
            h.close()
        handler = logging.FileHandler(root / "app.log")
        # %(name)s carries the participant ("server"/"{client_id}"):
        # an in-process cell interleaves every participant in ONE
        # app.log, and the protocol-model trace validator
        # (analysis/model.py events_from_log) needs it to replay each
        # participant's state machine separately
        handler.setFormatter(logging.Formatter(
            "%(asctime)s - %(name)s - %(levelname)s - %(message)s"))
        self._log.addHandler(handler)
        self._handler = handler

    def info(self, msg: str, color: str = "white") -> None:
        self._log.info(msg)
        if self.console:
            print_with_color(msg, color)

    def warning(self, msg: str) -> None:
        self._log.warning(msg)
        if self.console:
            print_with_color(msg, "yellow")

    def error(self, msg: str) -> None:
        self._log.error(msg)
        if self.console:
            print_with_color(msg, "red")

    def debug(self, msg: str) -> None:
        if self.debug_mode:
            self._log.debug(msg)
            if self.console:
                print_with_color(msg, "cyan")

    def sent(self, msg: str) -> None:
        """Outbound protocol message (reference's red ``[>>>]`` marker)."""
        self.info(f"[>>>] {msg}", "red")

    def received(self, msg: str) -> None:
        """Inbound protocol message (reference's blue ``[<<<]`` marker)."""
        self.info(f"[<<<] {msg}", "blue")

    def metric(self, **fields) -> None:
        """Append one structured metrics record (JSON line)."""
        rec = {"ts": time.time(), **fields}
        with open(self._metrics_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._handler.close()
        self._log.removeHandler(self._handler)
