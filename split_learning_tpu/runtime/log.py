"""Structured logging with console mirroring.

Parity with the reference logger (``/root/reference/src/Log.py``): a file
logger writing timestamped records to ``{log_path}/app.log``, mirrored to
the console with ANSI colors, with ``[>>>]``/``[<<<]`` direction markers
for protocol messages and a ``debug_mode`` gate.  Additions: per-round
structured metrics records (JSON lines in ``metrics.jsonl``) so runs are
machine-readable, which the reference lacks (SURVEY.md §5.5).

Console mirroring runs THROUGH the logging stack (a color-formatting
``StreamHandler`` attached only when ``console=True``) rather than raw
``print_with_color`` calls next to it: every console line shares the
file record's timestamp (so console output lines up with app.log and
the span journals), and the ``console=False`` gate is structural — no
code path can print around it.

Every metrics record is stamped with a run-scoped :data:`RUN_ID`, the
writing ``participant`` and an explicit ``kind`` (default ``round``)
so interleaved runs separate cleanly, and each line is flushed as
written so a crashed run keeps its tail.

Run-scoped layout (``observability.run-scoped``, default on via
:func:`make_logger`): the output files — ``app.log``,
``metrics.jsonl``, and the span journals (``runtime/spans.py`` uses
:func:`run_output_dir` for the same directory) — are written under
``{log_path}/artifacts/runs/{RUN_ID}/`` with compat symlinks at the
old top-level paths, so every existing consumer keeps working while
successive runs stop appending into one shared metrics.jsonl.  A
pre-existing REGULAR file at a compat path is rotated to ``*.prev``
once (legacy data preserved) before the symlink is placed; on
filesystems without symlink support the layout silently degrades to
the flat one.
"""

from __future__ import annotations

import json
import logging
import pathlib
import sys
import threading
import time
import uuid

_COLORS = {
    "red": "\033[91m", "green": "\033[92m", "yellow": "\033[93m",
    "blue": "\033[94m", "magenta": "\033[95m", "cyan": "\033[96m",
    "white": "\033[97m", "reset": "\033[0m",
}

#: run-scoped id: one per process, stamped on every metrics record (and
#: adoptable via ``Logger(run_id=...)`` when a driver coordinates
#: several processes of one logical run)
RUN_ID = uuid.uuid4().hex[:12]

_FMT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def run_output_dir(base: str | pathlib.Path,
                   run_id: str | None = None) -> pathlib.Path:
    """The run-scoped output directory under ``base``."""
    return pathlib.Path(base) / "artifacts" / "runs" / (run_id or RUN_ID)


def _proc_start(pid: int) -> str | None:
    """The pid's kernel start tick (/proc, Linux) — the identity that
    survives pid reuse; None where /proc is unavailable."""
    try:
        stat = pathlib.Path(f"/proc/{pid}/stat").read_text()
        # field 22 (starttime); comm (field 2) may contain spaces, so
        # split after the closing paren
        return stat.rsplit(")", 1)[1].split()[19]
    except (OSError, IndexError):
        return None


def write_run_owner(run_dir: pathlib.Path,
                    run_id: str | None = None) -> None:
    """Stamp ``run_dir/.owner`` with this process's pid + start tick:
    how :func:`compat_link` tells a LIVE concurrent process's link
    (follow it — multi-process deployments keep one merged metrics
    stream) from a DEAD previous run's (re-point it — a new run must
    not append into last week's directory)."""
    import os
    try:
        (run_dir / ".owner").write_text(
            f"{os.getpid()} {_proc_start(os.getpid()) or '-'} "
            f"{run_id or RUN_ID}\n")
    except OSError:
        pass


def _owner_alive(run_dir: pathlib.Path) -> bool:
    import os
    try:
        parts = (run_dir / ".owner").read_text().split()
        pid = int(parts[0])
    except (OSError, ValueError, IndexError):
        return False       # pre-owner-stamp runs are by definition dead
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        pass               # exists but not ours — keep checking
    # pid alive — but is it the SAME process?  After a reboot (or pid
    # wraparound) a recycled pid must not pin a dead run's symlink.
    stamped = parts[1] if len(parts) >= 3 else None
    if stamped and stamped != "-":
        return _proc_start(pid) == stamped
    return True


def compat_link(link: pathlib.Path, target: pathlib.Path) -> bool:
    """Best-effort compat symlink ``link -> target`` (relative).

    A pre-existing regular file is rotated aside to ``<name>.prev``
    (legacy cross-run data is preserved, not clobbered).  A symlink
    pointing at another run dir whose owner process is still ALIVE
    (``.owner`` pid, :func:`write_run_owner`) is a concurrent process
    of the same deployment and is left alone — returns False, and the
    caller falls back to the flat path, whose writes then resolve
    *through* the winner's link, keeping today's one-merged-file
    behavior (bench and the trace validator read the union).  A link
    whose owner is dead is a PREVIOUS run's leftover and is
    re-pointed, so new runs never append into old directories.  Also
    False when the filesystem refuses symlinks entirely."""
    import os
    try:
        rel = os.path.relpath(target, link.parent)
        if link.is_symlink():
            if os.readlink(link) == rel:
                return True
            old_target = (link.parent / os.readlink(link)).parent
            if _owner_alive(old_target):
                return False   # live concurrent process: follow it
            link.unlink()      # dead run's leftover: take over
        elif link.exists():
            prev = link.with_name(link.name + ".prev")
            if prev.exists():
                return False   # already rotated once; leave it alone
            link.rename(prev)
        try:
            link.symlink_to(rel)
        except FileExistsError:   # lost a creation race
            return link.is_symlink() and os.readlink(link) == rel
        return True
    except OSError:
        return False


def _scoped_root(root: pathlib.Path, run_id: str,
                 names: tuple = ("app.log", "metrics.jsonl")
                 ) -> pathlib.Path:
    """Resolve the run-scoped output dir + compat symlinks; falls back
    to ``root`` itself when symlinks are unavailable."""
    out = run_output_dir(root, run_id)
    try:
        out.mkdir(parents=True, exist_ok=True)
    except OSError:
        return root
    write_run_owner(out, run_id)
    for name in names:
        if not compat_link(root / name, out / name):
            return root
    return out

#: colors applied by level when the call site names none
_LEVEL_COLORS = {logging.WARNING: "yellow", logging.ERROR: "red",
                 logging.DEBUG: "cyan"}


def print_with_color(text: str, color: str = "white") -> None:
    """Raw colored stdout write (reference ``Log.py`` parity helper).
    Logger no longer routes console output here — its mirror runs
    through the :class:`_ColorFormatter` handler so every console line
    is timestamped and structurally gated by ``console=False``."""
    sys.stdout.write(f"{_COLORS.get(color, '')}{text}{_COLORS['reset']}\n")


class _ColorFormatter(logging.Formatter):
    """app.log format + ANSI color from ``extra={'color': ...}`` (or
    the level default), for the console mirror handler."""

    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        color = getattr(record, "color", None) \
            or _LEVEL_COLORS.get(record.levelno)
        if color in _COLORS:
            return f"{_COLORS[color]}{base}{_COLORS['reset']}"
        return base


class Logger:
    """File + console logger with structured metrics sidecar."""

    def __init__(self, log_path: str | pathlib.Path = ".",
                 debug: bool = False, console: bool = True,
                 name: str = "split_learning_tpu",
                 run_id: str | None = None, run_scoped: bool = False,
                 metrics_max_mb: float = 0.0, metrics_keep: int = 4):
        self.debug_mode = debug
        self.console = console
        self.participant = name
        self.run_id = run_id or RUN_ID
        # metrics.jsonl size-based rotation
        # (observability.metrics-max-mb): 0 disables; otherwise the
        # active file rotates to metrics.jsonl.1..keep once it crosses
        # the cap.  The ACTIVE path never changes, so the run-scoped
        # compat symlink stays valid across rotations (the rename +
        # reopen is atomic at the path level: os.replace).
        self._metrics_max = int(float(metrics_max_mb) * (1 << 20))
        self._metrics_keep = max(1, int(metrics_keep))
        root = pathlib.Path(log_path)
        root.mkdir(parents=True, exist_ok=True)
        # run-scoped layout: files land under artifacts/runs/<run_id>/
        # with compat symlinks at the flat paths (see module docstring)
        out = _scoped_root(root, self.run_id) if run_scoped else root
        self.output_dir = out
        self._metrics_path = out / "metrics.jsonl"
        self._metrics_lock = threading.Lock()
        self._metrics_f = None
        self._log = logging.getLogger(f"{name}.{id(self):x}")
        self._log.setLevel(logging.DEBUG)
        self._log.propagate = False
        # id() values recycle: a reused registry entry may still carry a
        # previous Logger's handler — drop any stale ones
        for h in list(self._log.handlers):
            self._log.removeHandler(h)
            h.close()
        handler = logging.FileHandler(out / "app.log")
        # %(name)s carries the participant ("server"/"{client_id}"):
        # an in-process cell interleaves every participant in ONE
        # app.log, and the protocol-model trace validator
        # (analysis/model.py events_from_log) needs it to replay each
        # participant's state machine separately
        handler.setFormatter(logging.Formatter(_FMT))
        self._log.addHandler(handler)
        self._handler = handler
        self._console_handler = None
        if console:
            ch = logging.StreamHandler(sys.stdout)
            ch.setFormatter(_ColorFormatter(_FMT))
            self._log.addHandler(ch)
            self._console_handler = ch

    def _emit(self, level: int, msg: str, color: str | None = None):
        self._log.log(level, msg,
                      extra=None if color is None else {"color": color})

    def info(self, msg: str, color: str = "white") -> None:
        self._emit(logging.INFO, msg, color)

    def warning(self, msg: str) -> None:
        self._emit(logging.WARNING, msg)

    def error(self, msg: str) -> None:
        self._emit(logging.ERROR, msg)

    def debug(self, msg: str) -> None:
        if self.debug_mode:
            self._emit(logging.DEBUG, msg)

    def sent(self, msg: str) -> None:
        """Outbound protocol message (reference's red ``[>>>]`` marker)."""
        self.info(f"[>>>] {msg}", "red")

    def received(self, msg: str) -> None:
        """Inbound protocol message (reference's blue ``[<<<]`` marker)."""
        self.info(f"[<<<] {msg}", "blue")

    def metric(self, **fields) -> None:
        """Append one structured metrics record (JSON line), stamped
        ``run_id``/``participant``/``kind`` and flushed immediately so
        a crashed run keeps every completed record."""
        rec = {"ts": time.time(), "run_id": self.run_id,
               "participant": self.participant,
               "kind": fields.pop("kind", "round")}
        rec.update(fields)
        line = json.dumps(rec) + "\n"
        with self._metrics_lock:
            if self._metrics_f is None or self._metrics_f.closed:
                self._metrics_f = open(self._metrics_path, "a")
            self._metrics_f.write(line)
            self._metrics_f.flush()
            if self._metrics_max and \
                    self._metrics_f.tell() >= self._metrics_max:
                self._rotate_metrics_locked()

    def _rotate_metrics_locked(self) -> None:
        """Shift metrics.jsonl -> .1 -> ... -> .keep (oldest dropped)
        and reopen the active path.  Readers (``sl_top --journal``,
        ``sl_perf``, the bench scavengers) glob ``metrics.jsonl*`` and
        read oldest-first, so a rotated run reads exactly like an
        unrotated one.  Best-effort: a failed rename must never kill
        the writer mid-round."""
        import os
        try:
            self._metrics_f.close()
            p = self._metrics_path
            oldest = p.with_name(f"{p.name}.{self._metrics_keep}")
            if oldest.exists():
                oldest.unlink()
            for i in range(self._metrics_keep - 1, 0, -1):
                src = p.with_name(f"{p.name}.{i}")
                if src.exists():
                    os.replace(src, p.with_name(f"{p.name}.{i + 1}"))
            os.replace(p, p.with_name(f"{p.name}.1"))
        except OSError:
            pass
        self._metrics_f = open(self._metrics_path, "a")

    @classmethod
    def for_run(cls, cfg, name: str, console: bool = False,
                run_id: str | None = None) -> "Logger":
        """Config-driven construction: honors
        ``observability.run-scoped`` (the entry points' path; direct
        ``Logger(...)`` keeps the flat layout for tools and tests)."""
        obs = getattr(cfg, "observability", None)
        return cls(cfg.log_path, debug=cfg.debug, console=console,
                   name=name, run_id=run_id,
                   run_scoped=bool(obs is not None and obs.run_scoped),
                   metrics_max_mb=getattr(obs, "metrics_max_mb", 0.0)
                   if obs is not None else 0.0,
                   metrics_keep=getattr(obs, "metrics_keep", 4)
                   if obs is not None else 4)

    def close(self) -> None:
        self._handler.close()
        self._log.removeHandler(self._handler)
        if self._console_handler is not None:
            self._log.removeHandler(self._console_handler)
            self._console_handler = None
        with self._metrics_lock:
            if self._metrics_f is not None and not self._metrics_f.closed:
                self._metrics_f.close()
