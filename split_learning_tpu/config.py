"""Validated configuration schema.

The reference parses a schemaless ``config.yaml`` with ``yaml.safe_load``
at every entry point (``/root/reference/server.py:12-17``,
``client.py:20-21``) and its five variants drift keys freely
(``manual`` vs ``no-cluster`` vs ``manual-cluster`` blocks).  Here the
union of all of those surfaces lives in one typed, validated schema:

* rounds {global, local}, wall-clock limit, per-stage client counts;
* model / dataset selection;
* cut topology {manual list, per-cluster lists, auto planner};
* data distribution {iid, dirichlet(alpha), fixed matrix};
* aggregation strategy {fedavg, periodic(t_c, t_g), fedasync(alpha),
  sequential relay, cluster relay, sda(size)};
* device selection on/off, cluster algorithm, cluster count;
* learning hyperparams incl. the in-flight cap (``control-count`` →
  microbatch count of the compiled schedule);
* checkpoint save/load/validate flags and paths;
* transport choice for the control plane.

Unknown keys are rejected (the reference silently ignores typos).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Any

import yaml


class ConfigError(ValueError):
    pass


#: LoRA kernel-name targets (single source of truth; ops/lora re-exports).
#: Mirrors the reference peft config [query, key, value, dense] where HF
#: "dense" suffix-matches the attention out-projection and both FFN
#: linears (src/RpcClient.py:61-66) — listed here under their flax names.
LORA_DEFAULT_TARGETS = ("query", "key", "value", "out", "dense",
                        "intermediate", "output", "mlp_in", "mlp_out")


def _check(cond: bool, msg: str):
    if not cond:
        raise ConfigError(msg)


@dataclasses.dataclass(frozen=True)
class LearningConfig:
    """Optimizer + loop hyperparameters (reference ``config.yaml:50-55``)."""
    learning_rate: float = 5e-4
    momentum: float = 0.9
    weight_decay: float = 0.0
    batch_size: int = 32
    # sgd | adamw | adamw-bf16 (both Adam moments stored bfloat16,
    # parallel/zero.py) | adamw-zero1 (bf16 moments additionally
    # flattened + sharded across the `stage` mesh axis — ZeRO-1; on
    # backends without a stage axis to shard over, protocol clients
    # already hold only their own stage's params and the optimizer
    # degrades to adamw-bf16)
    optimizer: str = "sgd"
    control_count: int = 4          # in-flight cap -> num_microbatches
    clip_grad_norm: float | None = None  # Vanilla_SL Scheduler.py:204-205
    # TPU-native extension (no reference equivalent): on device-resident
    # FedAvg rounds, CARRY adaptive-optimizer state across the round
    # barrier instead of re-initializing it each round.  The reference
    # (and the default here) rebuilds the optimizer per round, which
    # for Adam means the moments re-estimate from zero every few steps
    # — on small rounds that is the dominant source of the sawtooth
    # loss the flagship trajectory shows.  Params still FedAvg; moments
    # stay per-client (the standard local-Adam federated variant).
    opt_resident: bool = False
    lr_decay: float = 1.0           # DCSL Server.py:38-39
    lr_decay_every: int = 0         # rounds; 0 = off
    # LoRA adapters (reference peft wrap for BERT, RpcClient.py:61-66):
    # rank 0 disables; targets match kernel path names
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = LORA_DEFAULT_TARGETS
    # per-stage activation-recompute policy for the compiled pipeline
    # (parallel/pipeline.py): "wide" (default) checkpoints only stages
    # whose boundary exceeds the width threshold; "all" is the blanket
    # recompute; "none" stores every stage's activations
    remat: str = "wide"
    # Asynchronous decoupled split learning (ROADMAP item 2; *Decoupled
    # Split Learning via Auxiliary Loss*, arxiv 2601.19261 + staleness-
    # tolerant pipelining, arxiv 2412.14374).  "sync" (default) is the
    # reference's lockstep round; "async" decouples the backward wire:
    # every non-final stage trains against a LOCAL auxiliary head (no
    # Gradient frames at all — the gradient queues and their codecs go
    # dormant), the server folds Updates under a bounded-staleness
    # admission window instead of a full barrier, and clients keep
    # ticking on their current version while the next START streams in
    # (double-buffered seed swap at a tick boundary).
    mode: str = "sync"              # sync | async
    # auxiliary-head architecture built from the plan's cut shapes:
    # pooled-linear (mean-pool the boundary -> one Dense to classes) or
    # projection-mlp (pool -> Dense(hidden) -> gelu -> Dense(classes))
    aux_head: str = "pooled-linear"
    aux_hidden: int = 64            # projection-mlp hidden width
    # server admission window: an Update seeded from version v folds
    # iff server_version - v <= max-staleness, with its FedAvg weight
    # scaled by staleness-decay ** lag; older ones are rejected and
    # counted (agg_stale_updates)
    max_staleness: int = 2
    staleness_decay: float = 0.5
    # fresh (lag-0) contributions that cut a new global version; 0 =
    # every started client (the full barrier, maximally deterministic)
    async_quorum: int = 0
    # Round-boundary compute overlap in SYNC mode (the sync twin of the
    # async mode's pipelined rounds): after publishing its Update a
    # stage-1 client keeps working while the server folds, optimizes
    # and re-fans-out — it prefetches the next round's first batches
    # (loader draw + host->device transfer) and, when the previous
    # START held the local shard, runs the next round's first
    # microbatch FORWARDS on the stale seed.  The new params splice in
    # at the first tick boundary after START lands: speculative work
    # that matches the round's actual seed/loader is consumed in
    # place, anything else is discarded with the rng stream restored —
    # so an overlapped round is BIT-IDENTICAL to a non-overlapped one.
    sync_overlap: bool = False

    def validate(self):
        _check(self.remat in ("all", "wide", "none"),
               f"remat must be all|wide|none, got {self.remat!r}")
        _check(self.mode in ("sync", "async"),
               f"learning.mode must be sync|async, got {self.mode!r}")
        _check(self.aux_head in ("pooled-linear", "projection-mlp"),
               "learning.aux-head must be pooled-linear|projection-mlp, "
               f"got {self.aux_head!r}")
        _check(self.aux_hidden >= 1, "learning.aux-hidden must be >= 1")
        _check(self.max_staleness >= 0,
               "learning.max-staleness must be >= 0")
        _check(0.0 <= self.staleness_decay <= 1.0,
               "learning.staleness-decay must be in [0, 1], "
               f"got {self.staleness_decay!r}")
        _check(self.async_quorum >= 0,
               "learning.async-quorum must be >= 0 (0 = all clients)")
        _check(self.lora_rank >= 0, "lora-rank must be >= 0")
        _check(self.learning_rate > 0, "learning-rate must be > 0")
        _check(self.batch_size > 0, "batch-size must be > 0")
        _check(self.optimizer in ("sgd", "adamw", "adamw-bf16",
                                  "adamw-zero1"),
               "optimizer must be sgd|adamw|adamw-bf16|adamw-zero1, "
               f"got {self.optimizer!r}")
        _check(not (self.optimizer == "adamw-zero1"
                    and self.clip_grad_norm),
               "adamw-zero1 does not support clip-grad-norm (the "
               "sharded flat update has no global-norm view)")
        _check(not (self.optimizer == "adamw-zero1"
                    and self.lora_rank > 0),
               "adamw-zero1 does not support lora-rank > 0")
        _check(self.control_count > 0, "control-count must be > 0")


@dataclasses.dataclass(frozen=True)
class DistributionConfig:
    """Per-client label distribution synthesis (``src/Server.py:87-101``)."""
    mode: str = "iid"               # iid | dirichlet | fixed
    alpha: float = 1.0              # dirichlet concentration
    num_samples: int = 2500         # samples per stage-1 client
    matrix: tuple | None = None     # fixed per-client label counts (FLEX)
    seed: int | None = None
    # reference data-distribution.refresh (src/Server.py:48, consumed at
    # src/RpcClient.py:108): True -> every round re-samples each
    # client's label-count subset (loader rebuilt per START); False ->
    # the subset is drawn once and reused all training
    refresh: bool = False

    def validate(self):
        _check(self.mode in ("iid", "dirichlet", "fixed"),
               f"distribution mode must be iid|dirichlet|fixed, "
               f"got {self.mode!r}")
        if self.mode == "dirichlet":
            _check(self.alpha > 0, "dirichlet alpha must be > 0")
        if self.mode == "fixed":
            _check(self.matrix is not None,
                   "fixed distribution requires a matrix")


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Cut points + clustering (``config.yaml:25-34`` union)."""
    mode: str = "manual"            # manual | auto
    cut_layers: tuple = (7,)        # manual: one cut list for all clusters
    cluster_cut_layers: tuple | None = None  # per-cluster cut lists (FLEX)
    num_clusters: int = 1
    in_clusters: int = 1            # 2LS: in-clusters per out-cluster
    # (clients pair 1:1 edge<->head inside each; other/2LS/client.py:15-17)
    cluster_algorithm: str = "kmeans"  # kmeans | affinity
    selection: bool = False         # GMM straggler rejection on/off
    force_pipeline: bool = False    # keep stage ppermute even where the
    # backend would rather collapse to DP (CPU big-model safety fallback)
    # Reference clients refuse to start without profiling.json
    # (client.py:52-62); with require_profiles the server-side planner
    # restores that fail-fast contract: auto partitioning REJECTS
    # registrations without a usable profile instead of silently falling
    # back to an even layer split.
    require_profiles: bool = False
    # Elastic membership BETWEEN rounds (extension; the reference fixes
    # the client set at the registration barrier and a late client can
    # never join, src/Server.py:111-135): clients that REGISTER after
    # training started join the next round's plan, and clients that miss
    # consecutive round barriers are pruned from it (protocol backend).
    elastic_join: bool = False
    # Intra-client acceleration axes (fresh TPU surface, SURVEY.md §2.2):
    # shard each logical client's model over `model` (Megatron-style TP,
    # parallel/tensor.py), its sequence over `seq` (ring attention,
    # parallel/sequence.py), or its MoE experts over `expert`
    # (parallel/expert.py).  They compose with client DP (remaining
    # devices form the client axis); cuts are preserved as virtual
    # stages on each group.
    tensor_parallel: int = 1
    sequence_parallel: int = 1
    expert_parallel: int = 1

    def validate(self):
        _check(self.mode in ("manual", "auto"),
               f"topology mode must be manual|auto, got {self.mode!r}")
        _check(self.num_clusters >= 1, "num-clusters must be >= 1")
        _check(self.in_clusters >= 1, "in-clusters must be >= 1")
        _check(self.tensor_parallel >= 1 and self.sequence_parallel >= 1
               and self.expert_parallel >= 1,
               "tensor/sequence/expert-parallel must be >= 1")
        _check(sum(a > 1 for a in (self.tensor_parallel,
                                   self.sequence_parallel,
                                   self.expert_parallel)) <= 1,
               "at most one of tensor/sequence/expert-parallel may "
               "exceed 1 (each composes with client DP, not each other)")
        _check(self.cluster_algorithm in ("kmeans", "affinity"),
               f"cluster-algorithm must be kmeans|affinity, "
               f"got {self.cluster_algorithm!r}")
        if self.cluster_cut_layers is not None:
            _check(len(self.cluster_cut_layers) == self.num_clusters,
                   "cluster-cut-layers must have one entry per cluster")


@dataclasses.dataclass(frozen=True)
class AggregationConfig:
    """Round strategy knobs — the algorithm surface of the five variants
    (SURVEY.md §2.3) as configuration instead of code forks."""
    strategy: str = "fedavg"
    # fedavg | relay | cluster_relay | periodic | fedasync | sda
    t_client: int = 1               # FLEX t-c: client FedAvg interval
    t_global: int = 1               # FLEX t-g: global concat+validate interval
    fedasync_alpha: float | None = None  # 2LS: None -> 1/(1+rank)
    sda_size: int = 2               # DCSL server-side data-aggregation width
    # strict SDA barrier (VERDICT r3 weak #5): True = the window is a
    # HARD sda_size distinct-origin barrier (DCSL parity,
    # other/DCSL/src/Scheduler.py:152-191) — a slow-but-alive feeder is
    # waited for, and leftovers drain only on a feeder's epoch-end
    # marker or round PAUSE.  False (default) = elastic: an idle spell
    # flushes a partial window and the barrier adapts to live feeders.
    sda_strict: bool = False
    local_rounds: int = 1           # DCSL epochs per round
    # Streaming aggregation plane (runtime/aggregate.py, ROADMAP item
    # 4).  ``streaming`` (default on) folds each UPDATE into a running
    # per-stage weighted sum the moment the server decodes it, so the
    # UPDATE barrier holds O(1) parameter trees instead of O(clients);
    # a canonical (stage, client_id) reorder window keeps the result
    # bit-identical to the barrier fold.  Only strategies whose
    # aggregation consumes the whole update list at once stream
    # (fedavg/sda/cluster_relay); the others keep barrier semantics
    # automatically.
    streaming: bool = True
    # Aggregator tree: >= 2 interposes L1 aggregator participants
    # (clients -> L1 -> root) so per-node fan-in stays constant at
    # 100+ clients; groups of at most fan-in clients per stage fold
    # locally and publish one PartialAggregate.  0 = flat
    # direct-to-root.  An L1 that dies mid-round degrades to a counted
    # direct-to-root fallback drain.
    fan_in: int = 0
    # Tree depth (runtime/aggregate.py plan_tree): 1 = the classic
    # clients -> L1 -> root shape; >= 2 adds interior levels that
    # re-fold their children's PartialAggregates (sums of sums with
    # total weight — any depth divides exactly once at the root) so
    # the ROOT's fan-in stays constant at 10k+ clients too.  Stages
    # whose population already fits one group are not wrapped again.
    levels: int = 1
    # Run the aggregator tree in standalone AGGREGATOR PROCESSES
    # (runtime/aggnode.py, tools/sl_aggregator.py) adopted over the
    # broker instead of server threads: nodes announce with AggHello,
    # receive per-round AggAssign group assignments, heartbeat like
    # clients (FleetMonitor `lost` — or child-process exit — triggers
    # the same counted direct-to-root fallback drain an in-proc L1
    # death does).  False (default): thread-mode L1s, unchanged.
    remote: bool = False
    # With remote: the number of aggregator subprocesses the SERVER
    # spawns at startup (tcp transport only).  0 = adopt externally
    # started nodes (`python -m split_learning_tpu.aggregator`).
    nodes: int = 0
    # Run the running sum + FedAvg divide + server optimizer step as
    # jitted ops on arrays sharded across the server's device mesh
    # (MeshFoldBackend) instead of replicated host numpy trees.
    sharded: bool = False
    # Server-side optimizer on the aggregate (FedAvgM):
    # v' = m*v + (base - avg); new = base - v'.  0 (default) is plain
    # FedAvg — and keeps the bit-identity contract with the barrier
    # oracle.  Velocity state lives in the fold backend's (sharded)
    # representation between rounds.
    server_momentum: float = 0.0
    # Cross-replica-sharded weight update (arxiv 2004.13336): run the
    # entire round-boundary update — FedAvg divide, FedAvgM momentum
    # step, wire-dtype cast for START — as ONE fused program per
    # stage instead of per-leaf ops.  On the mesh backend
    # (aggregation.sharded) the fused program is jitted with donated
    # buffers and every leaf sharded along axis 0 over the `agg` mesh
    # axis, and the stage's result comes back in a single
    # device->host fetch; per-stage results stream to the START
    # fan-out in stage order while later stages are still updating.
    # Bit-identical to the per-leaf path (same elementwise IEEE ops in
    # the same order) — False keeps the legacy per-leaf path as the
    # parity oracle.
    update_sharded: bool = True

    def validate(self):
        _check(self.strategy in ("fedavg", "relay", "cluster_relay",
                                 "periodic", "fedasync", "sda"),
               f"unknown aggregation strategy {self.strategy!r}")
        _check(self.t_client >= 1 and self.t_global >= 1,
               "t-client/t-global must be >= 1")
        _check(self.sda_size >= 1, "sda-size must be >= 1")
        _check(self.local_rounds >= 1, "local-rounds must be >= 1")
        _check(self.fan_in == 0 or self.fan_in >= 2,
               f"aggregation.fan-in must be 0 (flat) or >= 2, "
               f"got {self.fan_in!r}")
        _check(not self.fan_in or self.streaming,
               "aggregation.fan-in requires aggregation.streaming "
               "(the root folds PartialAggregates incrementally)")
        _check(1 <= self.levels <= 4,
               f"aggregation.levels must be in 1..4, got {self.levels!r}")
        _check(self.levels == 1 or self.fan_in,
               "aggregation.levels > 1 requires aggregation.fan-in "
               "(the tree is built from fan-in groups)")
        _check(not self.remote or self.fan_in,
               "aggregation.remote requires aggregation.fan-in "
               "(remote nodes serve fan-in groups)")
        _check(self.nodes >= 0, "aggregation.nodes must be >= 0")
        _check(not self.nodes or self.remote,
               "aggregation.nodes requires aggregation.remote")
        _check(0.0 <= self.server_momentum < 1.0,
               f"aggregation.server-momentum must be in [0, 1), "
               f"got {self.server_momentum!r}")


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Save/load/validate flags (``config.yaml:11-13``).

    ``per_merge`` (2LS parity, ``other/2LS/src/Server.py:184``): under
    ``aggregation.strategy: fedasync`` also checkpoint after EVERY
    FedAsync in-cluster merge, not just at round end — the reference
    persists each alpha-merge so a crash mid-round loses at most one
    in-cluster's work.  Ignored by the other strategies (they have no
    mid-round global-model updates to persist)."""
    save: bool = True
    load: bool = False
    validate: bool = True
    directory: str = "checkpoints"
    per_merge: bool = False


@dataclasses.dataclass(frozen=True)
class TransportConfig:
    """Control-plane transport. ``inproc`` runs the whole cell in one
    process (TPU-native mode); ``tcp`` is the multi-process protocol mode
    replacing the reference's RabbitMQ creds (``config.yaml:36-43``)."""
    kind: str = "inproc"            # inproc | tcp
    host: str = "127.0.0.1"
    port: int = 5672
    # Activation/input-gradient float payload dtype on the data-plane
    # wire.  bf16 (the default) halves the per-hop bytes vs the
    # reference's fp32 pickles (src/train/VGG16.py:27); int8
    # absmax-quantizes each payload leaf for ~4x (runtime/protocol.py
    # QuantLeaf).  fp32 MASTER copies are untouched: weights in
    # START/UPDATE always travel full precision.  Aliases fp32/bf16/fp16
    # accepted.
    wire_dtype: str = "bfloat16"    # float32 | float16 | bfloat16 | int8
    # Async data plane (runtime/bus.py AsyncTransport, default on):
    # sends are enqueued into a bounded background sender (depth =
    # send-depth) that does the device fetch + TENSOR encode + socket
    # write off the training thread, and data-plane receives are pulled
    # prefetch-depth frames ahead by per-queue prefetchers.
    async_send: bool = True
    send_depth: int = 8
    prefetch_depth: int = 2
    # One frame's wire-size cap before it splits into crc'd chunks
    # (runtime/protocol.py encode_parts / FrameAssembler) — keeps a
    # giant UPDATE under the broker's frame sanity cap.
    chunk_mb: int = 512
    # Per-queue-family wire codec policy (runtime/codec/): a mapping of
    # queue family -> codec spec, e.g.
    #   codec: {intermediate: int8, gradient: "topk:0.05", rpc: delta}
    # intermediate takes tiled quantizers (int8[:tile] | int4[:tile]),
    # gradient additionally takes top-k + error-feedback
    # (topk:<frac>), rpc takes delta-encoded Updates
    # (delta | delta:bf16 | delta:int8[:tile]).  None = no codec; the
    # plain wire-dtype path applies.
    codec: Any = None
    # Global lossy wire dtypes are ambiguous now that per-queue codec
    # policies exist: ``wire-dtype: int8`` quantizes EVERY data-plane
    # payload with the blunt per-tensor legacy quantizer and composes
    # confusingly with a codec block.  It therefore requires this
    # explicit opt-in (and is always rejected alongside ``codec:``);
    # new configs should quantize via the codec block instead.
    allow_global_lossy: bool = False
    # At-least-once in-order delivery (runtime/bus.py ReliableTransport)
    # for queues matching ``reliable-queues``: sequence-numbered + ack'd
    # frames with bounded redelivery, receiver-side dedup + resequencing.
    # Default off — the plain queues are at-most-once, exactly the
    # reference's semantics; turn on for lossy/restarting brokers and
    # chaos runs.
    reliable: bool = False
    reliable_queues: tuple = ("intermediate_queue*", "gradient_queue*",
                              "rpc_queue", "aggregate_queue*")
    redeliver_s: float = 0.3        # first redelivery deadline (backoff x1.5)
    max_redeliver: int = 20         # bounded redelivery, then give up

    #: short spellings accepted for wire-dtype
    WIRE_DTYPE_ALIASES = {"fp32": "float32", "fp16": "float16",
                          "bf16": "bfloat16"}

    @property
    def wire_dtype_normalized(self) -> str:
        return self.WIRE_DTYPE_ALIASES.get(self.wire_dtype,
                                           self.wire_dtype)

    def validate(self):
        _check(self.kind in ("inproc", "tcp"),
               f"transport must be inproc|tcp, got {self.kind!r}")
        _check(self.wire_dtype_normalized in ("float32", "float16",
                                              "bfloat16", "int8"),
               f"wire-dtype must be float32|float16|bfloat16|int8 "
               f"(or fp32|fp16|bf16), got {self.wire_dtype!r}")
        from split_learning_tpu.runtime.codec.specs import (
            CodecSpecError, parse_codec_map,
        )
        try:
            parsed = parse_codec_map(self.codec)
        except CodecSpecError as e:
            raise ConfigError(f"transport.codec: {e}") from None
        if self.wire_dtype_normalized == "int8":
            _check(not parsed,
                   "transport.wire-dtype: int8 together with a "
                   "transport.codec block is ambiguous (two quantizers "
                   "would stack); move quantization into the codec "
                   "block, e.g. codec: {intermediate: int8, "
                   "gradient: int8}")
            _check(self.allow_global_lossy,
                   "transport.wire-dtype: int8 lossily quantizes EVERY "
                   "data-plane payload with the legacy per-tensor "
                   "quantizer; prefer the per-queue transport.codec "
                   "block, or set transport.allow-global-lossy: true "
                   "to opt in explicitly")
        _check(self.redeliver_s > 0, "redeliver-s must be > 0")
        _check(self.max_redeliver >= 1, "max-redeliver must be >= 1")
        _check(self.send_depth >= 1, "send-depth must be >= 1")
        _check(self.prefetch_depth >= 1, "prefetch-depth must be >= 1")
        _check(self.chunk_mb >= 1, "chunk-mb must be >= 1")


@dataclasses.dataclass(frozen=True)
class BrokerConfig:
    """Broker-plane shape (``runtime/bus.py``).

    ``shards`` > 1 splits the TCP broker into N independent shard
    processes on consecutive ports (``transport.port`` ..
    ``transport.port + shards - 1``).  Every participant maps each
    queue to its owning shard with the same deterministic
    ``shard_for`` hash (family-aware: a queue family's instances
    round-robin across shards, one queue never spans two), so the
    fleet's aggregate broker bandwidth scales with the shard count
    instead of serializing through one process.  A dead shard stalls
    only its own queues; per-shard reconnect backoff plus the
    reliable layer's redelivery recover it across a restart.  1
    (default) is the classic single broker — exactly the pre-sharding
    deployment.  Ignored by ``transport.kind: inproc`` (no broker
    process exists to shard)."""
    shards: int = 1
    #: seconds between the server's broker-plane stats sweeps (the
    #: /fleet "brokers" block + broker_* gauges); 0 disables polling
    stats_interval: float = 5.0

    def validate(self):
        _check(self.shards >= 1, "broker.shards must be >= 1")
        _check(self.shards <= 256,
               f"broker.shards must be <= 256, got {self.shards!r}")
        _check(self.stats_interval >= 0,
               "broker.stats-interval must be >= 0")


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """Deterministic fault injection (``runtime/chaos.py``).

    Every fault decision is drawn from a per-queue RNG seeded by
    ``(seed, queue)``, so a run's fault pattern is reproducible from the
    single ``chaos.seed`` — per queue, independent of scheduling — and a
    failure found in a chaos sweep replays exactly.  ``crash`` holds
    scripted crash points, e.g.::

        crash:
          - {client: client_1_1, queue: "intermediate_queue*", after: 2}

    meaning "client_1_1's process dies at its 2nd activation publish".
    Probabilities apply per published message on matching ``queues``."""
    enabled: bool = False
    seed: int = 0
    drop: float = 0.0               # message silently lost
    duplicate: float = 0.0          # message delivered twice
    reorder: float = 0.0            # message swapped behind its successor
    corrupt: float = 0.0            # one payload byte flipped
    delay: float = 0.0              # message held for delay-s
    delay_s: float = 0.02
    # rpc_queue included so EVERY tensor-framed message kind has a
    # default fault-injection point (slcheck PC006): Update rides
    # rpc_queue, and a wire type chaos can never touch is a recovery
    # path no soak ever exercises; aggregate_queue* covers the
    # aggregator-tree upload leg (Update -> L1) the same way
    queues: tuple = ("intermediate_queue*", "gradient_queue*",
                     "rpc_queue", "aggregate_queue*")
    crash: tuple = ()               # scripted crash points (dicts)

    def validate(self):
        for name in ("drop", "duplicate", "reorder", "corrupt", "delay"):
            v = getattr(self, name)
            _check(0.0 <= v <= 1.0,
                   f"chaos.{name} must be in [0, 1], got {v!r}")
        _check(self.delay_s >= 0, "chaos.delay-s must be >= 0")
        for spec in self.crash:
            _check(isinstance(spec, dict) and "client" in spec,
                   f"chaos.crash entries must be mappings with a "
                   f"'client' key, got {spec!r}")
            after = spec.get("after", 1)
            try:
                after = int(after)
            except (TypeError, ValueError):
                after = 0   # fall through to the clean error below
            _check(after >= 1,
                   f"chaos.crash 'after' must be an integer >= 1, "
                   f"got {spec.get('after', 1)!r}")


@dataclasses.dataclass(frozen=True)
class BlackboxConfig:
    """Per-process flight recorder (``runtime/blackbox.py``).

    Every process entry point installs a bounded in-memory event ring
    fed by the existing instrumentation seams (spans, transport
    publish/consume metadata, chaos injections, fault-counter deltas,
    scheduler decisions).  On abnormal exit (SIGTERM/SIGABRT,
    uncaught exception, sticky ChaosCrash) — or on demand via the
    server's ``BlackboxDump`` fan-out when any fleet member dies —
    the ring flushes an atomic ``blackbox-{participant}.json`` dump
    that ``tools/sl_postmortem.py`` assembles into a causal
    root-cause report.  ``ring-events`` bounds the ring (oldest
    events overwritten); ``dump-dir`` overrides where dumps land
    (default: the run-scoped artifacts directory, next to
    ``spans-*.jsonl`` and ``metrics.jsonl``)."""
    enabled: bool = True
    ring_events: int = 2048
    dump_dir: str | None = None

    def validate(self):
        _check(self.ring_events >= 16,
               f"observability.blackbox.ring-events must be >= 16, "
               f"got {self.ring_events!r}")


@dataclasses.dataclass(frozen=True)
class ObservabilityConfig:
    """Distributed round tracing (``runtime/spans.py``).

    Every participant journals spans to ``spans-{participant}.jsonl``
    (under ``journal-dir``, default the run's ``log_path``); the wire
    propagates a compact trace context on every TENSOR/chunk frame so
    publish and consume spans link across participants.
    ``tools/sl_trace.py`` merges the journals into a Perfetto
    ``trace.json`` and prints the per-round critical-path report.
    ``sample-rate`` thins the per-frame/per-batch spans (structural
    round/phase spans always record); latency histograms and counters
    are unaffected by sampling.

    Live telemetry plane (``runtime/telemetry.py``):
    ``heartbeat-interval`` is the period (seconds) of each client's
    background HEARTBEAT publish on the rpc queue (0 disables the
    plane entirely — no emitter threads, no FleetMonitor);
    ``liveness-timeout`` is how long the server's FleetMonitor lets a
    client stay silent before marking it ``lost`` — the state the
    round barriers drop instead of stalling until the 600 s RPC
    deadline; ``http-port`` (when set) serves ``/metrics`` (Prometheus
    text) and ``/fleet`` (JSON) from the server process (0 = an
    OS-assigned ephemeral port, logged at startup).

    ``run-scoped`` routes every output file (``app.log``,
    ``metrics.jsonl``, ``spans-*.jsonl``) under
    ``{journal-dir or log_path}/artifacts/runs/{run_id}/`` with compat
    symlinks at the old paths, so successive runs stop appending into
    one shared metrics.jsonl.

    Fleet-scale telemetry (``runtime/sketch.py``):
    ``digest-interval`` > 0 turns on the hierarchical heartbeat
    roll-up — clients' HEARTBEATs route to their aggregator node's
    digest queue and the server ingests one merged ``FleetDigest`` per
    node per interval (O(nodes), not O(clients)); the server keeps
    exact per-client state only for a ``watchlist-size``-bounded set
    (digest top-K / recent transitions / scheduler attention, with
    promotion/demotion hysteresis).  ``max-client-series`` caps the
    per-client ``sl_client_*`` cardinality on ``/metrics`` (watchlist
    first; the rest live in the fleet-level quantile families) and is
    the client count past which ``/fleet`` defaults to its summary
    shape.  ``metrics-max-mb`` > 0 rotates ``metrics.jsonl`` at that
    size (keeping ``metrics-keep`` rotated files) so long fleet runs
    cannot grow it without bound."""
    enabled: bool = True
    sample_rate: float = 1.0
    journal_dir: str | None = None      # None -> the run's log_path
    flush_every: int = 128              # span-journal buffer size
    heartbeat_interval: float = 2.0     # seconds; 0 = heartbeats off
    liveness_timeout: float = 45.0      # silent seconds -> lost
    http_port: int | None = None        # /metrics + /fleet; 0 = ephemeral
    run_scoped: bool = True             # artifacts/runs/<run_id>/ layout
    digest_interval: float = 0.0        # seconds; 0 = roll-up off
    max_client_series: int = 256        # /metrics sl_client_* cap
    watchlist_size: int = 64            # exact-state bound (digest mode)
    metrics_max_mb: float = 0.0         # metrics.jsonl rotation; 0 = off
    metrics_keep: int = 4               # rotated metrics.jsonl.N kept
    blackbox: BlackboxConfig = BlackboxConfig()  # flight recorder

    def validate(self):
        self.blackbox.validate()
        _check(0.0 <= self.sample_rate <= 1.0,
               f"observability.sample-rate must be in [0, 1], "
               f"got {self.sample_rate!r}")
        _check(self.flush_every >= 1,
               "observability.flush-every must be >= 1")
        _check(self.heartbeat_interval >= 0,
               "observability.heartbeat-interval must be >= 0")
        _check(self.liveness_timeout > self.heartbeat_interval,
               "observability.liveness-timeout must exceed the "
               "heartbeat interval")
        _check(self.http_port is None
               or 0 <= int(self.http_port) <= 65535,
               f"observability.http-port must be in [0, 65535], "
               f"got {self.http_port!r}")
        _check(self.digest_interval >= 0,
               "observability.digest-interval must be >= 0")
        _check(self.digest_interval == 0
               or self.heartbeat_interval > 0,
               "observability.digest-interval requires "
               "heartbeat-interval > 0 (digests roll up heartbeats)")
        _check(self.max_client_series >= 1,
               "observability.max-client-series must be >= 1")
        _check(self.watchlist_size >= 0,
               "observability.watchlist-size must be >= 0")
        _check(self.metrics_max_mb >= 0,
               "observability.metrics-max-mb must be >= 0")
        _check(self.metrics_keep >= 1,
               "observability.metrics-keep must be >= 1")


@dataclasses.dataclass(frozen=True)
class PerfConfig:
    """Compute performance-attribution plane (``runtime/perf.py``).

    ``sample-every`` is the step-sampling period of the hot-loop
    device fence: every Nth step is ``block_until_ready``-fenced to
    measure device wall (the other N-1 steps stay sync-free — the
    ``perf`` slcheck analyzer enforces that discipline statically).
    ``profile-dir`` overrides where on-demand ``POST /profile``
    captures land (default: the run-scoped output directory's
    ``profile/``).  ``datasheet`` overrides/extends the built-in
    per-``device_kind`` bf16 peak-TFLOPs table used as the MFU
    denominator — the supported way to pin a measured CPU roofline,
    e.g. ``datasheet: {cpu: 0.1}``."""
    enabled: bool = True
    sample_every: int = 16
    profile_dir: str | None = None
    datasheet: Any = None               # {device_kind: peak bf16 TFLOPs}

    def validate(self):
        _check(self.sample_every >= 1,
               "perf.sample-every must be >= 1")
        if self.datasheet is not None:
            _check(isinstance(self.datasheet, dict)
                   and all(isinstance(k, str) for k in self.datasheet),
                   "perf.datasheet must map device_kind -> TFLOPs")
            for k, v in self.datasheet.items():
                try:
                    ok = float(v) > 0
                except (TypeError, ValueError):
                    ok = False
                _check(ok, f"perf.datasheet[{k!r}] must be a positive "
                           f"number, got {v!r}")


@dataclasses.dataclass(frozen=True)
class SchedulerConfig:
    """Closed-loop resource-aware scheduler (``runtime/scheduler.py``).

    Runs at round boundaries on the protocol server, consuming the
    fleet-telemetry plane (per-client EWMA rate, compute rate, step
    p95, version lag — PRs 7/8/10) and closing the loop back into the
    plan: online clustering of clients, straggler demotion/eviction
    with per-client knob retunes, and measured-throughput cut
    re-planning.  Every decision is journaled as a ``kind=sched``
    metrics record (slcheck SC001 enforces that no control action is
    silent).  Default off — a static hand-written plan behaves exactly
    as before."""
    enabled: bool = False
    # decide every N rounds (1 = every round boundary)
    interval: int = 1
    # observe-only boundaries before the first action: the policies
    # need at least one round of telemetry to score against
    warmup_rounds: int = 1
    # online-clustering centroid count; 0 = one cluster per plan
    clusters: int = 0
    # mini-batch KMeans partial-fit cap per boundary: bounds the
    # decision cost so clustering stays O(minibatch) per round however
    # large the fleet grows (assignment stays O(n), vectorized)
    minibatch: int = 1024
    # sticky re-assignment margin: a client moves cluster only when the
    # new centroid is at least this fraction CLOSER than its current
    # one — the hysteresis that keeps assignments stable under churn
    hysteresis: float = 0.25
    # straggler eviction (through the elastic-drop path) on/off, and
    # how many consecutive scheduler boundaries a client must score
    # straggler before it is evicted rather than demoted
    evict: bool = True
    evict_after: int = 2
    # per-client knob demotion on/off
    demote: bool = True
    # codec retune shipped to WIRE-slow stragglers (START extra.sched):
    # any intermediate-family spec (runtime/codec/specs.py)
    wire_slow_codec: str = "int8:64"
    # extra bounded-staleness window granted to COMPUTE-slow stragglers
    # (async mode: their late Updates keep folding), and whether they
    # are exempted from quorum denominators
    staleness_bonus: int = 2
    # measured-throughput cut re-planning on/off, the damping threshold
    # (a new cut is adopted only when its predicted round wall improves
    # on the incumbent by at least this fraction — the anti-flap
    # contract), and the cooldown in rounds between adopted re-plans
    replan: bool = True
    replan_damping: float = 0.15
    replan_cooldown: int = 2
    # aggregator fan-in retuning (aggregation.fan-in >= 2 only): at
    # round boundaries the scheduler rescans the fan-in candidates
    # against the MEASURED per-contribution fold wall the kind=agg_node
    # heartbeats report, adopting a new tree width when the predicted
    # critical-path fold wall improves by replan-damping (same cooldown
    # as cut re-planning; journaled kind=sched action "retune")
    retune_fanin: bool = True
    # mid-round barrier policy: a NOTIFY/UPDATE barrier may drop a
    # health-state-straggler client after waiting this many seconds
    # (0 disables mid-round drops; lost clients are always droppable
    # via the fleet-liveness path regardless)
    barrier_grace_s: float = 20.0
    seed: int = 0

    def validate(self):
        _check(self.interval >= 1, "scheduler.interval must be >= 1")
        _check(self.warmup_rounds >= 0,
               "scheduler.warmup-rounds must be >= 0")
        _check(self.clusters >= 0, "scheduler.clusters must be >= 0")
        _check(self.minibatch >= 1, "scheduler.minibatch must be >= 1")
        _check(0.0 <= self.hysteresis < 1.0,
               f"scheduler.hysteresis must be in [0, 1), "
               f"got {self.hysteresis!r}")
        _check(self.evict_after >= 1,
               "scheduler.evict-after must be >= 1")
        _check(self.staleness_bonus >= 0,
               "scheduler.staleness-bonus must be >= 0")
        _check(0.0 <= self.replan_damping < 1.0,
               f"scheduler.replan-damping must be in [0, 1), "
               f"got {self.replan_damping!r}")
        _check(self.replan_cooldown >= 0,
               "scheduler.replan-cooldown must be >= 0")
        _check(self.barrier_grace_s >= 0,
               "scheduler.barrier-grace-s must be >= 0")
        from split_learning_tpu.runtime.codec.specs import (
            CodecSpecError, parse_codec_map,
        )
        try:
            parse_codec_map({"intermediate": self.wire_slow_codec})
        except CodecSpecError as e:
            raise ConfigError(
                f"scheduler.wire-slow-codec: {e}") from None


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    """Cross-host MPMD stage pipeline (``runtime/stagehost.py``).

    ``remote: true`` moves the pipeline's LATER-stage clients (the
    ``intermediate_queue_*`` consumers) out of the deployment's
    process group into standalone stage-host processes adopted over
    the broker via StageHello/StageAssign — stage-0 feeders stay
    wherever the deployment put them (they own the data).  The
    activation/gradient streams already ride broker queues as
    TENSOR/SLTC frames, so transport, codecs, generation fences and
    the async staleness plane compose unchanged; what changes is WHO
    polls those queues."""
    # Adopt stage hosts announced with StageHello and assign them the
    # later-stage client slots.  False (default): in-process later
    # stages, unchanged.
    remote: bool = False
    # With remote: the number of stage-host subprocesses the SERVER
    # spawns at startup (tcp transport only).  0 = adopt externally
    # started hosts (`python -m split_learning_tpu.stagehost`).
    hosts: int = 0
    # Per-round cap on counted slot re-assignments after a stage-host
    # death (FleetMonitor `lost` or child-process exit).  Each retry
    # re-assigns the dead host's slots to a survivor under the SAME
    # client ids and re-runs the round attempt behind a bumped
    # generation fence — the re-run fold is bit-identical to the
    # fault-free twin.  Exhausting retries fails the round loudly.
    retries: int = 2
    # With hosts: pin each spawned stage host to its own CPU core
    # (host i -> core (i+1) mod cpu_count; core 0 stays with the
    # server + feeders).  The NUMA-naive placement proxy the MPMD
    # bench cell uses so host processes don't migrate mid-measurement;
    # ignored when there are fewer cores than processes.
    pin_cpus: bool = False

    def validate(self):
        _check(self.hosts >= 0, "pipeline.hosts must be >= 0")
        _check(not self.hosts or self.remote,
               "pipeline.hosts requires pipeline.remote")
        _check(self.retries >= 0, "pipeline.retries must be >= 0")


@dataclasses.dataclass(frozen=True)
class KernelsConfig:
    """Pallas hot-path kernel plane (``ops/kernels/``).

    Per-kernel enables for the single-pass fused kernels that replace
    the XLA op chains on the two data-plane hot blocks: the tiled
    absmax quantize/dequantize codec path and the fused round-boundary
    ``stage_update``.  Off (default) keeps the pre-kernel XLA chains —
    byte-for-byte the old behavior.  When enabled, the same kernels
    run under the Pallas interpreter off-TPU and lower natively on
    TPU; the slcheck ``pallas`` analyzer (PK001) asserts an enabled
    kernel's ``pallas_call`` is actually present in the traced
    hot-path jaxpr."""
    # fused quantize (absmax reduce + scale + round/clip + NaN
    # sentinel + int4 nibble-pack in one VMEM pass) on the sender
    quantize: bool = False
    # the mirror fused dequantize on the receiver hot path
    dequantize: bool = False
    # fused FedAvg divide + FedAvgM momentum + wire-dtype cast inside
    # the sharded round-boundary update (aggregation.sharded)
    stage_update: bool = False
    # grid block target (tiles per quantize instance / axis-0 rows per
    # update instance); auto-shrunk to the largest exact divisor
    block: int = 128

    def validate(self):
        _check(self.block >= 1, "kernels.block must be >= 1")


@dataclasses.dataclass(frozen=True)
class Config:
    model: str = "VGG16"
    dataset: str = "CIFAR10"
    clients: tuple = (1, 1)         # per-stage client counts
    global_rounds: int = 1
    limited_time: float | None = None   # Vanilla_SL wall-clock budget (s)
    seed: int = 0
    debug: bool = False
    log_path: str = "."
    compute_dtype: str = "bfloat16"     # bfloat16 | float32
    # Persistent XLA compilation cache directory (default off): every
    # entry point applies it via platform.apply_compile_cache, so a
    # restarted process (the protocol deployment's cold round) reuses
    # compiled programs instead of re-paying the compile tax.
    compile_cache_dir: str | None = None
    model_kwargs: Any = None            # overrides for the model builder
    synthetic_size: int | None = None   # force synthetic datasets (tests)
    val_batch_size: int = 200
    val_max_batches: int | None = None
    learning: LearningConfig = LearningConfig()
    distribution: DistributionConfig = DistributionConfig()
    topology: TopologyConfig = TopologyConfig()
    aggregation: AggregationConfig = AggregationConfig()
    checkpoint: CheckpointConfig = CheckpointConfig()
    transport: TransportConfig = TransportConfig()
    broker: BrokerConfig = BrokerConfig()
    chaos: ChaosConfig = ChaosConfig()
    observability: ObservabilityConfig = ObservabilityConfig()
    perf: PerfConfig = PerfConfig()
    scheduler: SchedulerConfig = SchedulerConfig()
    pipeline: PipelineConfig = PipelineConfig()
    kernels: KernelsConfig = KernelsConfig()

    @property
    def model_key(self) -> str:
        """Registry key, reference naming: ``{MODEL}_{DATASET}``."""
        return f"{self.model}_{self.dataset}"

    @property
    def num_stages(self) -> int:
        return len(self.clients)

    def validate(self) -> "Config":
        _check(self.global_rounds >= 1, "global-rounds must be >= 1")
        _check(len(self.clients) >= 1 and all(c >= 1 for c in self.clients),
               "clients must be a non-empty list of positive counts")
        _check(self.compute_dtype in ("bfloat16", "float32"),
               f"compute-dtype must be bfloat16|float32, "
               f"got {self.compute_dtype!r}")
        for sub in (self.learning, self.distribution, self.topology,
                    self.aggregation, self.transport, self.broker,
                    self.chaos, self.observability, self.perf,
                    self.scheduler, self.pipeline, self.kernels):
            sub.validate()
        if self.scheduler.enabled:
            # the scheduler's only senses are the fleet-telemetry
            # plane's; with heartbeats disabled there is no
            # FleetMonitor and every policy would be blind
            _check(self.observability.heartbeat_interval > 0,
                   "scheduler.enabled requires "
                   "observability.heartbeat-interval > 0 (the "
                   "scheduler's inputs are the fleet-telemetry "
                   "plane's per-client series)")
        if self.learning.mode == "async":
            # the bounded-staleness admission window lives in the
            # streaming fold; strategies that consume individual
            # u.params (relay/periodic/fedasync) have no place to fold
            # a staleness-weighted late contribution
            _check(self.aggregation.strategy in ("fedavg", "sda",
                                                 "cluster_relay"),
                   "learning.mode: async requires a streaming-capable "
                   "aggregation strategy (fedavg|sda|cluster_relay), "
                   f"got {self.aggregation.strategy!r}")
            # the admission window LIVES in the streaming fold: with
            # streaming off there is nothing to fold a late Update
            # into (every stale contribution would be rejected), and
            # with an aggregator tree the L1s hard-fence on the
            # generation before the root ever sees the frame — both
            # would silently void the mode's staleness contract
            _check(self.aggregation.streaming,
                   "learning.mode: async requires "
                   "aggregation.streaming: true (the bounded-staleness "
                   "window folds into the streaming plane)")
            _check(self.aggregation.fan_in == 0,
                   "learning.mode: async does not compose with the "
                   "aggregator tree yet (L1 groups generation-fence "
                   "Updates before the admission window) — set "
                   "aggregation.fan-in: 0")
        if self.aggregation.nodes:
            _check(self.transport.kind == "tcp",
                   "aggregation.nodes (server-spawned aggregator "
                   "subprocesses) requires transport.kind: tcp — "
                   "in-process deployments adopt AggregatorNode "
                   "threads instead")
        if self.pipeline.hosts:
            _check(self.transport.kind == "tcp",
                   "pipeline.hosts (server-spawned stage-host "
                   "subprocesses) requires transport.kind: tcp — "
                   "in-process deployments adopt StageHost threads "
                   "instead")
        if self.topology.mode == "manual":
            cuts = self.topology.cluster_cut_layers or (
                self.topology.cut_layers,)
            for cl in cuts:
                _check(len(cl) == len(self.clients) - 1 or
                       len(self.clients) == 1,
                       f"manual cut list {cl!r} must have "
                       f"num_stages-1 = {len(self.clients) - 1} entries")
        return self


_SECTION_TYPES = {
    "learning": LearningConfig,
    "distribution": DistributionConfig,
    "topology": TopologyConfig,
    "aggregation": AggregationConfig,
    "checkpoint": CheckpointConfig,
    "transport": TransportConfig,
    "broker": BrokerConfig,
    "chaos": ChaosConfig,
    "observability": ObservabilityConfig,
    "perf": PerfConfig,
    "scheduler": SchedulerConfig,
    "pipeline": PipelineConfig,
    "kernels": KernelsConfig,
}


def _freeze(v):
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    return v


def _coerce(v, annotation: str):
    """YAML 1.1 parses ``5e-4`` (no dot) as a string; coerce strings into
    the field's declared numeric type so reference-style configs load."""
    if not isinstance(v, str):
        return v
    ann = annotation.replace(" ", "")
    try:
        if ann.startswith("float"):
            return float(v)
        if ann.startswith("int"):
            return int(v)
    except ValueError:
        pass
    return v


#: dataclass-typed fields NESTED inside a section (annotation name ->
#: class), so ``observability.blackbox: {...}`` builds a sub-config
#: instead of freezing to a plain dict
_NESTED_TYPES = {"BlackboxConfig": BlackboxConfig}


def _build(cls, d: dict, path: str):
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs = {}
    for k, v in d.items():
        key = k.replace("-", "_")
        _check(key in fields, f"unknown config key {path}{k!r}")
        ann = str(fields[key].type).replace(" ", "")
        if ann in _NESTED_TYPES:
            _check(isinstance(v, dict),
                   f"section {path}{k!r} must be a mapping")
            kwargs[key] = _build(_NESTED_TYPES[ann], v, f"{path}{k}.")
        else:
            kwargs[key] = _coerce(_freeze(v), ann)
    return cls(**kwargs)


def from_dict(d: dict[str, Any]) -> Config:
    top: dict[str, Any] = {}
    for k, v in d.items():
        key = k.replace("-", "_")
        if key in _SECTION_TYPES:
            _check(isinstance(v, dict),
                   f"section {k!r} must be a mapping")
            top[key] = _build(_SECTION_TYPES[key], v, f"{k}.")
        else:
            fields = {f.name: f for f in dataclasses.fields(Config)}
            _check(key in fields, f"unknown config key {k!r}")
            top[key] = _coerce(_freeze(v), str(fields[key].type))
    return Config(**top).validate()


def from_yaml(path: str | pathlib.Path) -> Config:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    _check(isinstance(data, dict), "config file must be a mapping")
    return from_dict(data)


def to_dict(cfg: Config) -> dict:
    return dataclasses.asdict(cfg)
