"""Single-process training cell: the whole deployment on one mesh.

The reference needs one ``server.py`` process, N ``client.py`` processes,
and a RabbitMQ broker to train at all (``/root/reference/README.md:144-171``).
On TPU the natural unit is one SPMD program, so this driver collapses the
deployment: logical clients are synthesized from the config's per-stage
counts, planned into clusters, and trained by the compiled mesh backend —
no transport in the hot path.  The multi-process protocol mode
(``python -m split_learning_tpu.server`` / ``.client``) shares every
piece of this except the context.

Usage::

    python -m split_learning_tpu.run --config config.yaml
"""

from __future__ import annotations

import argparse

from split_learning_tpu.config import Config, from_yaml
from split_learning_tpu.runtime.context import MeshContext
from split_learning_tpu.runtime.log import Logger
from split_learning_tpu.runtime.loop import TrainResult, run_training
from split_learning_tpu.runtime.plan import Registration, plan_clusters


def synthesize_registrations(cfg: Config,
                             profiles: dict | None = None) -> list:
    """Logical clients for in-process mode: ``client_{stage}_{i}`` per the
    config's per-stage counts (the reference's CLI ``--layer_id`` surface,
    ``client.py:14-17``)."""
    regs = []
    for stage, count in enumerate(cfg.clients, start=1):
        for i in range(count):
            cid = f"client_{stage}_{i}"
            regs.append(Registration(
                client_id=cid, stage=stage,
                profile=(profiles or {}).get(cid)))
    return regs


def run_local(cfg: Config, devices=None,
              logger: Logger | None = None,
              profiles: dict | None = None) -> TrainResult:
    from split_learning_tpu.parallel.multihost import ensure_initialized
    if ensure_initialized():
        import jax
        print(f"multi-host: process {jax.process_index()}"
              f"/{jax.process_count()}")
    logger = logger or Logger.for_run(cfg, "server", console=True)
    regs = synthesize_registrations(cfg, profiles)
    plans = plan_clusters(cfg, regs)
    ctx = MeshContext(cfg, devices=devices)
    try:
        return run_training(cfg, ctx, plans, logger)
    finally:
        ctx.shutdown()


def main(argv=None):
    from split_learning_tpu.platform import apply_platform_env
    apply_platform_env()
    ap = argparse.ArgumentParser(
        description="Run a full split-learning training cell in-process.")
    ap.add_argument("--config", default="config.yaml")
    args = ap.parse_args(argv)
    cfg = from_yaml(args.config)
    from split_learning_tpu.platform import apply_compile_cache
    apply_compile_cache(cfg.compile_cache_dir)
    from split_learning_tpu.runtime import blackbox
    blackbox.install(cfg, "server", role="server")
    result = run_local(cfg)
    for rec in result.history:
        acc = (f" val_acc={rec.val_accuracy:.4f}"
               if rec.val_accuracy is not None else "")
        print(f"round {rec.round_idx}: ok={rec.ok} "
              f"samples={rec.num_samples}{acc}")


if __name__ == "__main__":
    main()
