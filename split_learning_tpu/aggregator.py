"""``python -m split_learning_tpu.aggregator`` — standalone aggregator
node entry (``aggregation.remote``, ``runtime/aggnode.py``)."""

from split_learning_tpu.runtime.aggnode import main

if __name__ == "__main__":
    main()
