"""``python -m split_learning_tpu.stagehost`` — standalone stage-host
entry (``pipeline.remote``, ``runtime/stagehost.py``)."""

from split_learning_tpu.runtime.stagehost import main

if __name__ == "__main__":
    main()
