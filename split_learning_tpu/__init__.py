"""TPU-native split-learning framework.

A ground-up JAX/XLA re-design of the capabilities of filrg/split_learning
(see SURVEY.md): layer-indexed model partitioning across pipeline stages,
pipelined activation/gradient exchange over ICI via collective permutes,
weighted FedAvg aggregation with a cluster hierarchy, and a profile-driven
planner (KMeans clustering, GMM device selection, max-min throughput cut
search) that emits a ``jax.sharding.Mesh`` assignment instead of a queue
topology.
"""

__version__ = "0.1.0"

import split_learning_tpu.compat  # noqa: F401  (jax.shard_map bridge)
from split_learning_tpu.planner import (  # noqa: F401
    partition,
    auto_threshold,
    kmeans_cluster,
    synthesize_label_counts,
)
from split_learning_tpu.ops.fedavg import fedavg_trees  # noqa: F401
