"""Jaxpr hot-path auditor.

Two complementary passes over the compiled training path:

* **Jaxpr pass** (JX002-JX004): build a tiny
  :class:`~split_learning_tpu.runtime.client.ShardRunner` (KWT on
  synthetic MFCC shapes — the cheapest registered model), trace its
  jitted train ops to jaxprs with ``jax.make_jaxpr`` over
  ``jax.eval_shape``-derived parameter shapes (zero FLOPs, no
  compile), and flag:

  - JX002 — fp32 upcasts on the bf16 wire path: the hot loop's
    wire-bound outputs (stage boundary activations / input gradients,
    after the device-side wire cast) carry a float dtype wider than
    ``transport.wire-dtype``, so every tick fetches double the bytes
    the wire will ship;
  - JX003 — host round-trips compiled into the step (callback /
    infeed / outfeed primitives) and float64 avals (x64 drift blows
    the recompile cache and doubles buffer sizes);
  - JX004 — nondeterministic trace: tracing the same op twice yields
    different jaxprs (a ``time``/``random`` call leaked into trace
    time — every retrace recompiles).

* **AST pass** (JX001, JX005, JX006): walk the tick-loop sources and
  flag

  - JX001 — implicit device→host syncs inside a hot loop:
    ``float()``/``int()``/``bool()``/``.item()``/``np.asarray``/
    ``jax.device_get``/``block_until_ready`` applied to a jitted op's
    result (or any ``jnp.*`` expression).  Escape hatch for audited
    syncs: trailing ``# slcheck: allow-sync``;
  - JX005 — donated-then-reused buffers: a call to a train step whose
    maker donates argument positions must rebind those arguments from
    the result in the same statement (the convention every call site
    follows — a later read of a donated buffer is undefined);
  - JX006 — ``jax.jit`` invoked inside a loop body (a fresh jit wrapper
    per iteration defeats the compile cache);
  - JX007 — non-donated round-boundary update buffers: in the
    aggregation plane (``runtime/aggregate.py``) every ``jax.jit``
    whose function takes a running-accumulator parameter (``acc`` /
    ``stat_acc`` — the module's naming convention) must donate those
    positions, or each fold/update allocates a fresh full-stage buffer
    instead of updating in place.  The jaxpr pass additionally traces
    the fused sharded stage update (``MeshFoldBackend.stage_update``)
    and flags host round-trips compiled into it (JX003) and
    fp32-upcast-on-bf16-wire outputs (a leaf declared bf16 must come
    back bf16 — JX002) — the buffer-donation audit the sharded
    weight-update plane is gated by.
"""

from __future__ import annotations

import ast
import pathlib
import re

from split_learning_tpu.analysis.findings import Finding

#: hot functions per source file; "loops" audits loop bodies only,
#: "all" audits the whole body (helpers invoked per tick)
HOT_FUNCTIONS = {
    "split_learning_tpu/runtime/client.py": {
        "_train_whole": "loops", "_train_first": "loops",
        "_train_middle": "loops", "_train_last": "loops",
        "_sda_step": "all",
    },
    "split_learning_tpu/runtime/context.py": {
        "_drive_columns": "loops",
    },
}

#: attribute names of the jitted ops a ShardRunner / pipeline exposes
_JIT_OPS = {"fwd", "bwd", "last_step", "whole_step", "apply_update",
            "step"}
_SYNC_CALLS = {"float", "int", "bool"}
_SYNC_ATTRS = {"item", "tolist", "block_until_ready", "device_get"}
_ANNOT_RE = re.compile(r"#\s*slcheck:\s*(.+?)\s*$")


def _annotated(source_lines: list[str], lineno: int, tag: str) -> bool:
    if 0 < lineno <= len(source_lines):
        m = _ANNOT_RE.search(source_lines[lineno - 1])
        return bool(m and tag in m.group(1))
    return False


def _is_jnp_expr(node: ast.AST) -> bool:
    """Does this expression root in a jnp./jax. call?"""
    while isinstance(node, ast.Call):
        node = node.func
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in ("jnp", "jax")


class _HotLoopVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, fn_name: str, mode: str,
                 source_lines: list[str]):
        self.rel = rel
        self.fn_name = fn_name
        self.mode = mode
        self.lines = source_lines
        self.loop_depth = 0
        self.device_names: set[str] = set()
        self.findings: list[Finding] = []

    def _note_assign(self, node: ast.Assign) -> None:
        val = node.value
        is_dev = False
        if isinstance(val, ast.Call):
            f = val.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            is_dev = name in _JIT_OPS or _is_jnp_expr(val)
        if is_dev:
            for t in node.targets:
                for n in ([t] if isinstance(t, ast.Name)
                          else list(getattr(t, "elts", []))):
                    if isinstance(n, ast.Name):
                        self.device_names.add(n.id)

    def _in_hot_region(self) -> bool:
        return self.mode == "all" or self.loop_depth > 0

    def _flag(self, node: ast.AST, what: str) -> None:
        if _annotated(self.lines, node.lineno, "allow-sync"):
            return
        self.findings.append(Finding(
            "JX001", self.rel, node.lineno, self.fn_name,
            f"implicit device->host sync in hot loop: {what}"))

    def visit_Assign(self, node: ast.Assign):
        self._note_assign(node)
        self.generic_visit(node)

    def _visit_loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = _visit_loop

    def _arg_is_device(self, arg: ast.AST) -> bool:
        if _is_jnp_expr(arg):
            return True
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Name) and sub.id in self.device_names:
                return True
        return False

    def visit_Call(self, node: ast.Call):
        if self._in_hot_region():
            f = node.func
            if isinstance(f, ast.Name) and f.id in _SYNC_CALLS \
                    and node.args and self._arg_is_device(node.args[0]):
                self._flag(node, f"{f.id}({ast.unparse(node.args[0])})")
            elif isinstance(f, ast.Attribute):
                if f.attr in _SYNC_ATTRS and self._arg_is_device(
                        node.args[0] if node.args else f.value):
                    self._flag(node, f"{ast.unparse(f)}(...)")
                elif f.attr == "asarray" and isinstance(f.value, ast.Name) \
                        and f.value.id == "np" and node.args \
                        and self._arg_is_device(node.args[0]):
                    self._flag(node,
                               f"np.asarray({ast.unparse(node.args[0])})")
                elif f.attr == "jit":
                    self.findings.append(Finding(
                        "JX006", self.rel, node.lineno, self.fn_name,
                        "jax.jit called inside a loop body: every "
                        "iteration builds a fresh wrapper and defeats "
                        "the compile cache"))
        self.generic_visit(node)


def _audit_hot_loops(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel, funcs in HOT_FUNCTIONS.items():
        path = root / rel
        source = path.read_text()
        lines = source.splitlines()
        tree = ast.parse(source)
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name in funcs:
                v = _HotLoopVisitor(rel, node.name, funcs[node.name],
                                    lines)
                v.visit(node)
                findings += v.findings
    return findings


# -- donated-then-reused ----------------------------------------------------
# Convention (parallel/pipeline.py make_*_train_step): a step called as
#   params, opt, stats, loss = step(params, opt, stats, x, labels, rngs)
# donates positions (0, 1, 2); the frozen/LoRA variant
#   t, opt, stats, loss = step(frozen, t, opt, stats, x, labels, rngs)
# donates (1, 2, 3).  Call sites must rebind every donated argument
# from the result tuple IN THE SAME STATEMENT.

def _audit_donation(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    rel = "split_learning_tpu/runtime/context.py"
    tree = ast.parse((root / rel).read_text())
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        if not (isinstance(val, ast.Call)
                and isinstance(val.func, ast.Name)
                and val.func.id == "step"):
            continue
        n_args = len(val.args)
        if n_args not in (6, 7):
            continue   # not the train-step convention (e.g. eval step)
        donated = (1, 2, 3) if n_args == 7 else (0, 1, 2)
        targets: set[str] = set()
        for t in node.targets:
            for n in ([t] if isinstance(t, ast.Name)
                      else list(getattr(t, "elts", []))):
                if isinstance(n, ast.Name):
                    targets.add(n.id)
        for pos in donated:
            if pos >= n_args:
                continue
            arg = val.args[pos]
            if isinstance(arg, ast.Name) and arg.id not in targets:
                findings.append(Finding(
                    "JX005", rel, node.lineno, "step-call",
                    f"donated argument {arg.id!r} (position {pos}) is "
                    "not rebound from the step result: the buffer is "
                    "invalid after the call"))
    return findings


# -- round-boundary update donation (JX007) ---------------------------------
# Convention (runtime/aggregate.py): a jitted op whose function takes a
# running-accumulator parameter — named `acc` / `stat_acc` — consumes
# that buffer (the fold adds in place, the fused stage update finishes
# it).  Not donating it doubles the aggregation plane's residency and
# adds a full-stage copy per call.

_UPDATE_BUF_PARAMS = {"acc", "stat_acc"}
_UPDATE_REL = "split_learning_tpu/runtime/aggregate.py"


def _scan_update_donation(source: str, rel: str) -> list[Finding]:
    findings: list[Finding] = []
    tree = ast.parse(source)
    defs = {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef)}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "jit" and node.args):
            continue
        fn = node.args[0]
        if isinstance(fn, ast.Lambda):
            params = [a.arg for a in fn.args.args]
        elif isinstance(fn, ast.Name) and fn.id in defs:
            params = [a.arg for a in defs[fn.id].args.args]
        else:
            continue
        positions = [i for i, p in enumerate(params)
                     if p in _UPDATE_BUF_PARAMS]
        if not positions:
            continue
        donated: tuple = ()
        for kw in node.keywords:
            if kw.arg == "donate_argnums":
                try:
                    val = ast.literal_eval(kw.value)
                except ValueError:
                    val = None
                if isinstance(val, int):
                    donated = (val,)
                elif isinstance(val, (tuple, list)):
                    donated = tuple(val)
        missing = [params[i] for i in positions if i not in donated]
        if missing:
            findings.append(Finding(
                "JX007", rel, node.lineno, "jit",
                "round-boundary update buffer(s) "
                f"{missing!r} not in donate_argnums: every fold/update "
                "call allocates a fresh full-stage buffer instead of "
                "updating in place"))
    return findings


def _audit_update_donation(root: pathlib.Path) -> list[Finding]:
    return _scan_update_donation((root / _UPDATE_REL).read_text(),
                                 _UPDATE_REL)


def _audit_update_jaxpr(root: pathlib.Path) -> list[Finding]:
    """Trace the fused sharded stage update (the round-boundary
    program per stage) and audit it like the train ops: no host
    round-trip primitives (JX003), and no fp32-upcast leaving the
    program — a leaf the START will ship as bf16 must come back bf16
    (JX002), or every round fetches (and pins in the shadow) double
    the bytes the wire carries."""
    import jax
    import numpy as np

    try:
        import ml_dtypes
        bf16 = np.dtype(ml_dtypes.bfloat16)
    except ImportError:  # pragma: no cover - jax ships it
        bf16 = np.dtype(np.float16)
    from split_learning_tpu.runtime.aggregate import (
        MeshFoldBackend, _StageFold,
    )

    findings: list[Finding] = []
    be = MeshFoldBackend()
    st = _StageFold(["c0"])
    declared = {"layer0/k": bf16, "layer0/b": np.dtype(np.float32),
                "layer0/step": np.dtype(np.int32)}
    st.dtype = dict(declared)
    st.total_w = 2.0
    st.acc = {
        "layer0/k": be.contrib(np.ones((8, 4), bf16), 2.0),
        "layer0/b": be.contrib(np.ones((4,), np.float32), 2.0),
        "layer0/step": be.contrib(np.asarray(3, np.int32), 2.0),
    }
    base_flat = {"layer0/k": np.ones((8, 4), np.float32),
                 "layer0/b": np.ones((4,), np.float32)}
    params, stats, _ = be.stage_fetch(
        be.stage_update(st, base_flat, {}, 0.9))
    for path, dt in declared.items():
        got = np.asarray(params[path]).dtype
        if got != dt:
            findings.append(Finding(
                "JX002", _UPDATE_REL, 0, "stage_update",
                f"fused update returns {path} as {got} but the START "
                f"wire dtype is {dt}: cast on device before the "
                "fetch"))
    # the program the call above compiled-and-cached, traced abstractly
    for prog in be._fused_cache.values():
        jaxpr = jax.make_jaxpr(
            lambda acc, stat, base, vel: prog(
                acc, stat, base, vel, np.float32(2.0),
                np.float32(0.0), np.float32(0.9)))(
            {p: np.ones((8, 4), np.float32) if p == "layer0/k"
             else (np.ones((4,), np.float32) if p == "layer0/b"
                   else np.float32(6.0))
             for p in declared},
            {}, dict(base_flat),
            {p: np.zeros_like(v) for p, v in base_flat.items()})
        _scan_jaxpr(jaxpr, _UPDATE_REL, "stage_update", findings)
    return findings


# -- jaxpr pass -------------------------------------------------------------

_AUDIT_MODEL = "KWT_SPEECHCOMMANDS"
_AUDIT_KWARGS = {"embed_dim": 16, "num_heads": 2, "mlp_dim": 32}
_AUDIT_INPUT = (2, 40, 98)   # synthetic MFCC batch (data/datasets.py)


def _scan_jaxpr(jaxpr, rel: str, where: str,
                findings: list[Finding]) -> None:
    import jax.numpy as jnp

    seen = set()

    def walk(jx):
        if id(jx) in seen:
            return
        seen.add(id(jx))
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if any(tag in name for tag in
                   ("callback", "infeed", "outfeed")):
                findings.append(Finding(
                    "JX003", rel, 0, where,
                    f"host round-trip primitive {name!r} compiled "
                    "into the step"))
            for sub in eqn.params.values():
                inner = getattr(sub, "jaxpr", None)
                if inner is not None:
                    walk(inner)
        for var in list(jx.invars) + list(jx.outvars):
            dt = getattr(getattr(var, "aval", None), "dtype", None)
            if dt == jnp.float64:
                findings.append(Finding(
                    "JX003", rel, 0, where,
                    "float64 aval in the step jaxpr (x64 drift)"))
                return

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def _audit_jaxprs(root: pathlib.Path,
                  wire_dtype: str = "bfloat16") -> list[Finding]:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from split_learning_tpu.runtime.client import (
        ShardRunner, _cast_for_wire, device_wire_dtype, _wire_np_dtype,
    )

    rel = "split_learning_tpu/runtime/client.py"
    findings: list[Finding] = []
    runner = ShardRunner(_AUDIT_MODEL, 0, -1, {"batch_size": 2},
                         model_kwargs=dict(_AUDIT_KWARGS))
    x = jax.ShapeDtypeStruct(_AUDIT_INPUT, jnp.float32)
    rng = jax.random.key(0)
    variables = jax.eval_shape(
        lambda k: runner.model.init(k, jnp.zeros(_AUDIT_INPUT,
                                                 jnp.float32),
                                    train=False), rng)
    params = variables["params"]
    stats: dict = {}
    frozen: dict = {}
    t = {"lora": {}, "head": params}
    dev_dtype = device_wire_dtype(_wire_np_dtype(wire_dtype))

    def wire_fwd(f, tt, s, xx, k):
        # mirror the hot loop: jitted fwd, then the device-side wire
        # cast that runs before the device->host fetch
        return _cast_for_wire(runner.fwd(f, tt, s, xx, k), dev_dtype)

    jaxpr = jax.make_jaxpr(wire_fwd)(frozen, t, stats, x, rng)
    _scan_jaxpr(jaxpr, rel, "fwd", findings)
    wire_np = _wire_np_dtype(wire_dtype)
    # int8 wire quantizes host-side (QuantLeaf); there is no device
    # cast to audit, so the width check only covers float wires
    wire_width = (None if np.dtype(wire_np) == np.int8
                  else np.dtype(wire_np).itemsize)
    out_shapes = jax.eval_shape(wire_fwd, frozen, t, stats, x, rng)
    for leaf in jax.tree_util.tree_leaves(out_shapes):
        if (wire_width is not None
                and jnp.issubdtype(leaf.dtype, jnp.floating)
                and np.dtype(leaf.dtype).itemsize > wire_width):
            findings.append(Finding(
                "JX002", rel, 0, "fwd",
                f"wire-bound activation leaves the device as "
                f"{leaf.dtype} but transport.wire-dtype is "
                f"{wire_dtype}: cast on device before the fetch"))
            break
    # the backward path's input-gradient feeds the wire the same way
    ct = out_shapes

    def wire_bwd(f, tt, s, xx, cc, k):
        gt, gx, new_stats = runner.bwd(f, tt, s, xx, cc, k)
        return _cast_for_wire(gx, dev_dtype)

    jaxpr_b = jax.make_jaxpr(wire_bwd)(frozen, t, stats, x, ct, rng)
    _scan_jaxpr(jaxpr_b, rel, "bwd", findings)
    gx_shapes = jax.eval_shape(wire_bwd, frozen, t, stats, x, ct, rng)
    for leaf in jax.tree_util.tree_leaves(gx_shapes):
        dt = getattr(leaf, "dtype", None)
        if wire_width is not None and dt is not None \
                and jnp.issubdtype(dt, jnp.floating) \
                and np.dtype(dt).itemsize > wire_width:
            findings.append(Finding(
                "JX002", rel, 0, "bwd",
                f"wire-bound input gradient leaves the device as {dt} "
                f"but transport.wire-dtype is {wire_dtype}"))
            break
    # retrace determinism: an identical second trace proves no
    # time/random call leaked into trace time (every retrace would
    # otherwise compile a fresh program)
    again = jax.make_jaxpr(wire_fwd)(frozen, t, stats, x, rng)
    if str(jaxpr) != str(again):
        findings.append(Finding(
            "JX004", rel, 0, "fwd",
            "re-tracing the train step produced a different jaxpr: "
            "trace-time nondeterminism forces recompiles"))
    return findings


def run(root: pathlib.Path, trace: bool = True) -> list[Finding]:
    findings = _audit_hot_loops(root)
    findings += _audit_donation(root)
    findings += _audit_update_donation(root)
    if trace:
        from split_learning_tpu.config import TransportConfig
        wire = TransportConfig().wire_dtype_normalized
        findings += _audit_jaxprs(root, wire)
        findings += _audit_update_jaxpr(root)
    return findings
