"""Counter/histogram/gauge name-registry conformance (CT001-CT004).

``FaultCounters.inc``, ``HistogramSet.observe`` and ``GaugeSet.set``
are string-keyed: a typo'd name does not fail — it silently mints a
fresh key that no dashboard, test or metrics consumer ever reads,
while the intended counter stays flat.  The runtime therefore declares
its full name vocabulary in ``runtime/trace.py``
(:data:`FAULT_COUNTER_NAMES`, :data:`HISTOGRAM_NAMES`,
:data:`GAUGE_NAMES`) and this analyzer enforces, statically, that
every ``.inc("name", ...)`` / ``.observe("name", ...)`` /
``.set("name", ...)`` call with a string-literal first argument
anywhere in the package or ``tools/`` uses a declared name.

Non-literal names are deliberately ignored (they are always derived
from an iteration over declared names today); test files are excluded
(tests may fabricate names to prove the analyzer works).  The ``.set``
rule only fires on string-literal first arguments, so
``Event().set()`` (no args) and jax's ``.at[idx].set(v)`` (non-string)
never match.

CT004 extends the same contract to the digest roll-up plane
(``runtime/sketch.py``): the counter/gauge vocabularies the digest
path declares (``DIGEST_COUNTER_NAMES`` / ``DIGEST_GAUGE_NAMES``) must
be SUBSETS of the trace.py registries — a digest counter outside
``FAULT_COUNTER_NAMES`` would mint a key no exporter family ever
renders, the exact silent-drop CT001 exists to prevent, one level up.
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding

#: (method name, finding code, registry attribute on runtime.trace)
_RULES = {
    "inc": ("CT001", "FAULT_COUNTER_NAMES", "FaultCounters counter"),
    "observe": ("CT002", "HISTOGRAM_NAMES", "latency histogram"),
    "set": ("CT003", "GAUGE_NAMES", "GaugeSet gauge"),
}


def _registries() -> dict[str, frozenset]:
    from split_learning_tpu.runtime import trace
    return {attr: getattr(trace, attr)
            for _, (_, attr, _) in _RULES.items()}


def scan_source(source: str, rel: str,
                registries: dict[str, frozenset] | None = None
                ) -> list[Finding]:
    """All undeclared counter/histogram names in one source file."""
    regs = registries if registries is not None else _registries()
    findings: list[Finding] = []
    tree = ast.parse(source)
    # enclosing-function names make the fingerprints stable
    where_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                lineno = getattr(sub, "lineno", None)
                if lineno is not None:
                    where_of.setdefault(lineno, node.name)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RULES and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue
        code, reg_attr, what = _RULES[node.func.attr]
        if arg.value in regs[reg_attr]:
            continue
        findings.append(Finding(
            code, rel, node.lineno,
            where_of.get(node.lineno, arg.value),
            f"undeclared {what} name {arg.value!r} — add it to "
            f"runtime/trace.py {reg_attr} (or fix the typo)"))
    return findings


def check_digest_registries(
        registries: dict[str, frozenset] | None = None,
        digest_counters: frozenset | None = None,
        digest_gauges: frozenset | None = None) -> list[Finding]:
    """CT004: every name the digest plane declares must exist in the
    matching trace.py registry (parameters exist for the negative
    tests; production callers pass nothing)."""
    regs = registries if registries is not None else _registries()
    if digest_counters is None or digest_gauges is None:
        from split_learning_tpu.runtime import sketch
        if digest_counters is None:
            digest_counters = sketch.DIGEST_COUNTER_NAMES
        if digest_gauges is None:
            digest_gauges = sketch.DIGEST_GAUGE_NAMES
    rel = "split_learning_tpu/runtime/sketch.py"
    findings: list[Finding] = []
    for name in sorted(digest_counters - regs["FAULT_COUNTER_NAMES"]):
        findings.append(Finding(
            "CT004", rel, 1, "DIGEST_COUNTER_NAMES",
            f"digest counter {name!r} is not declared in "
            "runtime/trace.py FAULT_COUNTER_NAMES — its increments "
            "would never reach sl_faults_total"))
    for name in sorted(digest_gauges - regs["GAUGE_NAMES"]):
        findings.append(Finding(
            "CT004", rel, 1, "DIGEST_GAUGE_NAMES",
            f"digest gauge {name!r} is not declared in "
            "runtime/trace.py GAUGE_NAMES — its sets would never "
            "render on /metrics"))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    regs = _registries()
    findings: list[Finding] = []
    paths = sorted(
        list((root / "split_learning_tpu").rglob("*.py"))
        + list((root / "tools").glob("*.py")))
    for path in paths:
        rel = str(path.relative_to(root))
        try:
            source = path.read_text()
        except OSError:
            continue
        findings += scan_source(source, rel, regs)
    findings += check_digest_registries(regs)
    return findings
