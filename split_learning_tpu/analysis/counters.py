"""Counter/histogram/gauge name-registry conformance (CT001-CT003).

``FaultCounters.inc``, ``HistogramSet.observe`` and ``GaugeSet.set``
are string-keyed: a typo'd name does not fail — it silently mints a
fresh key that no dashboard, test or metrics consumer ever reads,
while the intended counter stays flat.  The runtime therefore declares
its full name vocabulary in ``runtime/trace.py``
(:data:`FAULT_COUNTER_NAMES`, :data:`HISTOGRAM_NAMES`,
:data:`GAUGE_NAMES`) and this analyzer enforces, statically, that
every ``.inc("name", ...)`` / ``.observe("name", ...)`` /
``.set("name", ...)`` call with a string-literal first argument
anywhere in the package or ``tools/`` uses a declared name.

Non-literal names are deliberately ignored (they are always derived
from an iteration over declared names today); test files are excluded
(tests may fabricate names to prove the analyzer works).  The ``.set``
rule only fires on string-literal first arguments, so
``Event().set()`` (no args) and jax's ``.at[idx].set(v)`` (non-string)
never match.
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding

#: (method name, finding code, registry attribute on runtime.trace)
_RULES = {
    "inc": ("CT001", "FAULT_COUNTER_NAMES", "FaultCounters counter"),
    "observe": ("CT002", "HISTOGRAM_NAMES", "latency histogram"),
    "set": ("CT003", "GAUGE_NAMES", "GaugeSet gauge"),
}


def _registries() -> dict[str, frozenset]:
    from split_learning_tpu.runtime import trace
    return {attr: getattr(trace, attr)
            for _, (_, attr, _) in _RULES.items()}


def scan_source(source: str, rel: str,
                registries: dict[str, frozenset] | None = None
                ) -> list[Finding]:
    """All undeclared counter/histogram names in one source file."""
    regs = registries if registries is not None else _registries()
    findings: list[Finding] = []
    tree = ast.parse(source)
    # enclosing-function names make the fingerprints stable
    where_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                lineno = getattr(sub, "lineno", None)
                if lineno is not None:
                    where_of.setdefault(lineno, node.name)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RULES and node.args):
            continue
        arg = node.args[0]
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            continue
        code, reg_attr, what = _RULES[node.func.attr]
        if arg.value in regs[reg_attr]:
            continue
        findings.append(Finding(
            code, rel, node.lineno,
            where_of.get(node.lineno, arg.value),
            f"undeclared {what} name {arg.value!r} — add it to "
            f"runtime/trace.py {reg_attr} (or fix the typo)"))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    regs = _registries()
    findings: list[Finding] = []
    paths = sorted(
        list((root / "split_learning_tpu").rglob("*.py"))
        + list((root / "tools").glob("*.py")))
    for path in paths:
        rel = str(path.relative_to(root))
        try:
            source = path.read_text()
        except OSError:
            continue
        findings += scan_source(source, rel, regs)
    return findings
