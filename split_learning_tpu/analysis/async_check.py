"""``async`` analyzer — bounded-staleness admission discipline.

**AS001**: every server-side fold call site must go through (or sit
inside) the staleness admission window.  The async mode
(``learning.mode: async``) replaces the hard generation fence with an
admission check — ``server_version - version <= learning.max-staleness``
— applied in ``runtime/server.py _admit_update``.  A new fold call site
(``*.add_update(...)`` / ``*.add_partial(...)``) added to the server
WITHOUT that check would silently fold arbitrarily stale contributions
(or re-fold duplicates) the moment someone wires it into the pump:
exactly the class of bug the window exists to prevent.

Rule: a fold call site in ``runtime/server.py`` passes iff its
enclosing function references the admission window (the
``max_staleness`` knob or the ``_admit_update`` door) — or carries the
``# slcheck: async-exempt`` annotation naming it a sync-path site whose
inputs are already generation-fenced upstream (the L1 fallback drain,
PartialAggregate folding: L1 members are never stale-admitted).
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding
from split_learning_tpu.analysis.protocol_check import _annotations

#: server files held to the admission-window rule ("server-side fold
#: call site" — the aggregation plane itself and the client are not
#: admission doors)
FILES = ("split_learning_tpu/runtime/server.py",)

#: methods that fold a contribution into a streaming fold
FOLD_CALLS = frozenset({"add_update", "add_partial"})

#: references that prove the enclosing function checks the window
ADMISSION_REFS = frozenset({"max_staleness", "_admit_update"})

_EXEMPT = "async-exempt"


def _admission_guarded(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Attribute) and n.attr in ADMISSION_REFS:
            return True
        if isinstance(n, ast.Name) and n.id in ADMISSION_REFS:
            return True
    return False


def check_source(source: str, rel: str) -> list[Finding]:
    tree = ast.parse(source)
    notes = _annotations(source)
    findings: list[Finding] = []

    # lexically enclosing function per fold call
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                if isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute) \
                        and child.func.attr in FOLD_CALLS:
                    # innermost function wins (walk visits outer first,
                    # so later assignment = inner function)
                    parents[id(child)] = node

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in FOLD_CALLS):
            continue
        if _EXEMPT in notes.get(node.lineno, ""):
            continue
        fn = parents.get(id(node))
        if fn is not None and _admission_guarded(fn):
            continue
        findings.append(Finding(
            "AS001", rel, node.lineno, "",
            f"fold call `{node.func.attr}` outside the staleness "
            "admission window — route it through _admit_update (or "
            "check learning.max_staleness in the enclosing function), "
            "or annotate '# slcheck: async-exempt' if its inputs are "
            "generation-fenced upstream"))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel in FILES:
        path = root / rel
        if path.exists():
            findings += check_source(path.read_text(), rel)
    return findings
