"""Pallas lowering gate (PK001).

A config-enabled Pallas kernel that silently falls back to an XLA op
chain is the worst kind of perf regression: numerically identical,
invisible to every correctness test, and the exact failure mode a
refactor of the dispatch plumbing would produce.  This analyzer closes
the gap the jaxpr audit leaves open — JX001-007 prove the hot path has
no host round-trips, but nothing proved the kernels the config claims
are on actually ARE the compiled path.

PK001: for every kernel the plane can enable, trace the REAL hot-path
entry point with that kernel enabled and require a ``pallas_call``
primitive somewhere in the jaxpr (recursing through sub-jaxprs, so
jit/custom-vjp wrapping doesn't hide it):

* quantize — ``_quantize_dev`` (int8 and the int4 nibble-pack shape)
  with a kernel block;
* dequantize — ``_dequantize_dev`` mirror;
* stage_update — a real :class:`MeshFoldBackend` built with
  ``stage_update`` enabled, driven through ``stage_update`` exactly
  like the JX007 jaxpr audit, then every cached fused program traced;
* flash attention — the llama decoder path (``use_flash=True``): a
  tiny TinyLlama forward traced end to end, proving the model-level
  flag still routes through the Pallas kernel in the compiled step
  (before this gate, nothing asserted that).

:func:`check_lowering` is a pure jaxpr->findings helper so the
negative test can prove the gate actually fires on a pallas-free
program.  Requires tracing (jax): a ``--no-trace`` run skips this
analyzer entirely.
"""

from __future__ import annotations

import pathlib

from split_learning_tpu.analysis.findings import Finding

_REL_QUANT = "split_learning_tpu/runtime/codec/quant.py"
_REL_AGG = "split_learning_tpu/runtime/aggregate.py"
_REL_FLASH = "split_learning_tpu/ops/flash_attention.py"


def contains_pallas_call(jaxpr) -> bool:
    """True iff a ``pallas_call`` primitive appears anywhere in the
    (closed) jaxpr, including nested sub-jaxprs."""
    seen: set = set()

    def walk(jx) -> bool:
        if id(jx) in seen:
            return False
        seen.add(id(jx))
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                return True
            for sub in eqn.params.values():
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and walk(inner):
                    return True
                # pallas_call itself carries the kernel as a plain
                # Jaxpr param; custom_vjp/jit carry ClosedJaxprs —
                # both expose .jaxpr, lists carry several
                if isinstance(sub, (list, tuple)):
                    for s in sub:
                        inner = getattr(s, "jaxpr", None)
                        if inner is not None and walk(inner):
                            return True
        return False

    return walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def check_lowering(jaxpr, rel: str, where: str) -> list[Finding]:
    """PK001 on one traced program: the enabled kernel's
    ``pallas_call`` must be present, or the config is lying about what
    the hot path runs."""
    if contains_pallas_call(jaxpr):
        return []
    return [Finding(
        "PK001", rel, 0, where,
        f"kernel {where!r} is enabled but no pallas_call primitive "
        "appears in the traced hot-path jaxpr: the kernel silently "
        "fell back to the XLA chain")]


def _check_codec_kernels(block: int) -> list[Finding]:
    import jax
    import numpy as np

    from split_learning_tpu.runtime.codec.quant import (
        _dequantize_dev, _quantize_dev,
    )

    findings: list[Finding] = []
    x = np.ones((33, 5), np.float32)
    for bits, tile in ((8, 64), (4, 7)):
        jaxpr = jax.make_jaxpr(
            lambda a, b=bits, t=tile: _quantize_dev(
                a, t, b, kernel_block=block))(x)
        findings += check_lowering(jaxpr, _REL_QUANT,
                                   f"quantize:int{bits}")
    # mirror: well-formed tiled codes for both widths
    for bits, tile, codes in ((8, 64, np.zeros((192,), np.int8)),
                              (4, 7, np.zeros((84,), np.uint8))):
        scale = np.ones((codes.shape[0] * (2 if bits == 4 else 1)
                         // tile,), np.float32)
        jaxpr = jax.make_jaxpr(
            lambda q, s, b=bits, t=tile: _dequantize_dev(
                q, s, t, b, 160, (160,), kernel_block=block))(
            codes, scale)
        findings += check_lowering(jaxpr, _REL_QUANT,
                                   f"dequantize:int{bits}")
    return findings


def _check_stage_update_kernel(block: int) -> list[Finding]:
    """Build a mesh backend with the stage-update kernel enabled,
    drive one real stage_update (compiling + caching its fused
    program), then trace each cached program and require the
    pallas_call — the same trace shape as the JX007 jaxpr audit."""
    import jax
    import numpy as np

    from split_learning_tpu.ops.kernels import KernelPlan
    from split_learning_tpu.runtime.aggregate import (
        MeshFoldBackend, _StageFold,
    )

    findings: list[Finding] = []
    be = MeshFoldBackend(kernels=KernelPlan(stage_update=True,
                                            block=block))
    st = _StageFold(["c0"])
    st.dtype = {"layer0/k": np.dtype(np.float32),
                "layer0/step": np.dtype(np.int32)}
    st.total_w = 2.0
    st.acc = {"layer0/k": be.contrib(np.ones((8, 4), np.float32), 2.0),
              "layer0/step": be.contrib(np.asarray(3, np.int32), 2.0)}
    base_flat = {"layer0/k": np.ones((8, 4), np.float32)}
    be.stage_fetch(be.stage_update(st, base_flat, {}, 0.9))
    if not be._fused_cache:
        return [Finding(
            "PK001", _REL_AGG, 0, "stage_update",
            "stage_update compiled no fused program to audit")]
    for prog in be._fused_cache.values():
        jaxpr = jax.make_jaxpr(
            lambda acc, base, vel: prog(
                acc, {}, base, vel, np.float32(2.0), np.float32(1.0),
                np.float32(0.9)))(
            {"layer0/k": np.ones((8, 4), np.float32),
             "layer0/step": np.float32(6.0)},
            dict(base_flat),
            {"layer0/k": np.zeros((8, 4), np.float32)})
        findings += check_lowering(jaxpr, _REL_AGG, "stage_update")
    return findings


def _check_flash_lowering() -> list[Finding]:
    """The llama attention path: a tiny TinyLlama with
    ``use_flash=True`` traced end to end must keep ``flash_attention``
    as a pallas_call in the compiled step."""
    import jax
    import jax.numpy as jnp

    from split_learning_tpu.models import build_model

    m = build_model("TinyLlama_TINYSTORIES", use_flash=True,
                    vocab_size=64, hidden_size=32, num_heads=4,
                    num_kv_heads=2, intermediate_size=64, n_block=1)
    x = jnp.zeros((1, 8), jnp.int32)
    variables = jax.eval_shape(
        lambda k: m.init(k, x, train=False), jax.random.key(0))
    jaxpr = jax.make_jaxpr(
        lambda p, xx: m.apply({"params": p}, xx, train=False))(
        variables["params"], x)
    return check_lowering(jaxpr, _REL_FLASH, "llama-flash-attention")


def run(root: pathlib.Path, trace: bool = True) -> list[Finding]:
    if not trace:
        return []
    from split_learning_tpu.config import KernelsConfig
    block = KernelsConfig().block
    findings = _check_codec_kernels(block)
    findings += _check_stage_update_kernel(block)
    findings += _check_flash_lowering()
    return findings
