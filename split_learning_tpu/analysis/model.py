"""Declarative protocol model + trace validator.

The runtime's message state machine lives implicitly across
``runtime/client.py`` (lifecycle loop + hot loops) and
``runtime/server.py`` (rpc pump + round choreography).  This module
lifts it into one declarative description — states x frame kinds x
legal transitions — that serves three consumers:

* the **AST conformance checker**
  (:mod:`split_learning_tpu.analysis.protocol_check`) verifies every
  send/recv site in the source names a frame type, queue family and
  direction the model allows;
* the **trace validator** (:func:`validate_events` /
  :func:`validate_log`) replays a recorded run — the ``app.log``
  protocol markers or a decoded frame stream — and flags transition
  sequences the model forbids (``tools/run_chaos.py`` runs it at the
  end of every sweep cell);
* the **instrumented tests** use it as the oracle for deliberately
  broken sequences.

Model vocabulary
----------------

Queue families (patterns as in ``runtime/protocol.py``):

=============  =======================  ===============================
family         pattern                  direction
=============  =======================  ===============================
rpc            ``rpc_queue``            any client -> server
reply          ``reply_{client_id}``    server -> one client (clients
                                        may re-queue Start/Stop to
                                        their OWN reply queue to unwind
                                        a hot loop)
intermediate   ``intermediate_queue_*`` stage k -> stage k+1
gradient       ``gradient_queue_*``     stage k+1 -> one stage-k client
=============  =======================  ===============================
"""

from __future__ import annotations

import dataclasses
import fnmatch
import re

from split_learning_tpu.analysis.findings import Finding

# -- wire vocabulary --------------------------------------------------------

CONTROL_KINDS = ("Register", "Ready", "Notify", "Update",
                 "Start", "Syn", "Pause", "Stop", "Heartbeat",
                 "PartialAggregate", "AggHello", "AggAssign",
                 "AggFlush", "FleetDigest", "DigestRoute",
                 "StageHello", "StageAssign", "BlackboxDump")
DATA_KINDS = ("Activation", "Gradient", "EpochEnd")
ALL_KINDS = CONTROL_KINDS + DATA_KINDS

QUEUE_FAMILIES = {
    "rpc": "rpc_queue",
    "reply": "reply_*",
    "intermediate": "intermediate_queue_*",
    "gradient": "gradient_queue_*",
    # aggregator tree (aggregation.fan-in, runtime/aggregate.py):
    # clients of one L1 group -> that group's aggregator
    "aggregate": "aggregate_queue_*",
    # hierarchical heartbeat roll-up (observability.digest-interval,
    # runtime/sketch.py): clients routed to an aggregator node publish
    # their HEARTBEATs here; the node folds them into FleetDigest
    # frames for the server
    "digest": "digest_queue_*",
}

#: legal (sender-role, queue-family, kind) triples.  The two
#: ("client", "reply", ...) rows are the self-requeue paths: a hot loop
#: that sees a Start/Stop mid-round re-publishes it to its OWN reply
#: queue so the lifecycle loop can unwind (client.py _redeliver_*).
SEND_RULES = frozenset({
    ("client", "rpc", "Register"), ("client", "rpc", "Ready"),
    ("client", "rpc", "Notify"), ("client", "rpc", "Update"),
    ("client", "rpc", "Heartbeat"),
    ("server", "reply", "Start"), ("server", "reply", "Syn"),
    ("server", "reply", "Pause"), ("server", "reply", "Stop"),
    ("client", "reply", "Start"), ("client", "reply", "Stop"),
    ("client", "intermediate", "Activation"),
    ("client", "intermediate", "EpochEnd"),
    ("client", "gradient", "Gradient"),
    # aggregator tree (aggregation.fan-in): a grouped client uploads
    # its round UPDATE to its L1's aggregate queue instead of rpc; the
    # L1 (runtime/aggregate.py L1Aggregator — the third protocol role)
    # folds the group and publishes one PartialAggregate to the root
    ("client", "aggregate", "Update"),
    ("aggregator", "rpc", "PartialAggregate"),
    # multi-process aggregator tree (aggregation.remote / levels,
    # runtime/aggnode.py): a standalone node announces itself for
    # adoption and heartbeats like a client; the server assigns its
    # groups and flushes it over its reply queue; interior levels
    # relay partials through the parent group's aggregate queue — and
    # the server's fallback publishes a SUBSTITUTE partial there when
    # the child's aggregator died (runtime/server.py _flush_fallback)
    ("aggregator", "rpc", "AggHello"),
    ("aggregator", "rpc", "Heartbeat"),
    ("server", "reply", "AggAssign"),
    ("server", "reply", "AggFlush"),
    ("aggregator", "aggregate", "PartialAggregate"),
    ("server", "aggregate", "PartialAggregate"),
    # hierarchical heartbeat roll-up (observability.digest-interval):
    # a routed client beats into its node's digest queue, the node
    # publishes one merged FleetDigest per interval on rpc, and the
    # server re-points a dead node's clients with DigestRoute frames
    # on their reply queues
    ("client", "digest", "Heartbeat"),
    ("aggregator", "rpc", "FleetDigest"),
    ("server", "reply", "DigestRoute"),
    # MPMD cross-host stage pipeline (pipeline.remote,
    # runtime/stagehost.py): a standalone stage-host process announces
    # itself for adoption and heartbeats like a client; the server
    # assigns (and, on host death, RE-assigns mid-round) the
    # later-stage client slots over the host's reply queue.  The
    # host's INNER per-slot clients are ordinary clients — their
    # traffic is covered by the client rows above.
    ("stagehost", "rpc", "StageHello"),
    ("stagehost", "rpc", "Heartbeat"),
    ("server", "reply", "StageAssign"),
    # fleet flight recorder (runtime/blackbox.py): when any
    # participant dies the server fans a BlackboxDump out to every
    # SURVIVING participant's reply queue; each recipient flushes its
    # local ring to disk — no reply frame, the dumps are the answer
    ("server", "reply", "BlackboxDump"),
})

#: queue families each role may consume from.  The server's aggregate
#: entry is the direct-to-root fallback: when an L1 dies mid-round the
#: server drains the orphaned group queue itself
#: (``runtime/aggregate.py drain_group_queue``).
RECV_RULES = frozenset({
    ("server", "rpc"), ("server", "aggregate"),
    ("client", "reply"), ("client", "intermediate"),
    ("client", "gradient"),
    ("aggregator", "aggregate"),
    # remote aggregator node: AggAssign/AggFlush/Stop on its reply
    # queue (runtime/aggnode.py AggregatorNode.run)
    ("aggregator", "reply"),
    # heartbeat roll-up: the node's DigestWorker drains its digest
    # queue; the server drains a DEAD node's queue itself (the
    # fallback — parked beats are liveness proof, not losses)
    ("aggregator", "digest"), ("server", "digest"),
    # stage host: StageAssign/Stop on its reply queue
    # (runtime/stagehost.py StageHost.run)
    ("stagehost", "reply"),
})

#: kinds legal on each DATA queue family (post-transport stream)
DATA_RULES = {
    "intermediate": frozenset({"Activation", "EpochEnd"}),
    "gradient": frozenset({"Gradient"}),
}


def queue_family(queue: str) -> str | None:
    for fam, pat in QUEUE_FAMILIES.items():
        if fnmatch.fnmatchcase(queue, pat) or queue == pat:
            return fam
    return None


# -- control-plane state machines -------------------------------------------
# Transitions are {state: {(direction, kind): next_state}}; directions
# are from the OWNING role's point of view ("send" = it published).
# Stop may arrive/be sent at almost any point (teardown races are legal)
# — that is part of the model, not looseness: the runtime really does
# accept it everywhere.

SERVER_FSM: dict[str, dict[tuple[str, str], str]] = {
    "idle": {
        ("recv", "Register"): "idle",
        ("send", "Start"): "starting",
        ("send", "Stop"): "stopped",
    },
    "starting": {                       # STARTs out, READY barrier
        ("send", "Start"): "starting",
        ("recv", "Register"): "starting",
        ("recv", "Ready"): "starting",
        # async bounded-staleness admission (learning.mode: async): a
        # straggler's Update seeded from an older version can land —
        # and fold, staleness-weighted — at ANY point of the next
        # invocation, not just during the UPDATE barrier
        ("recv", "Update"): "starting",
        # remote aggregator tree: group assignments fan out between
        # the START fan-out and SYN (after the READY barrier narrowed
        # the membership)
        ("send", "AggAssign"): "starting",
        ("send", "Syn"): "running",
        ("send", "Stop"): "stopped",
    },
    "running": {                        # training; NOTIFY barrier
        ("recv", "Notify"): "running",
        ("recv", "Register"): "running",
        # async: stale-admitted straggler Update (see "starting")
        ("recv", "Update"): "running",
        # async: a late READY still gets its SYN after the fan-out
        # (the READY barrier collapsed to the responsive set; the
        # straggler joins late instead of idling out the round)
        ("send", "Syn"): "running",
        ("recv", "Ready"): "running",
        ("send", "Pause"): "pausing",
        ("send", "Stop"): "stopped",
    },
    "pausing": {                        # UPDATE collection
        ("recv", "Update"): "pausing",
        ("recv", "PartialAggregate"): "pausing",  # L1 group flushes
        ("recv", "Notify"): "pausing",   # straggler NOTIFY still legal
        ("recv", "Register"): "pausing",
        # async late READY during the UPDATE barrier (the SYN window
        # stays open until the version cut)
        ("recv", "Ready"): "pausing",
        ("send", "Syn"): "pausing",
        # remote aggregator tree: the server releases straggler-held
        # nodes (AggFlush) and, when a child aggregator died, publishes
        # the fallback's SUBSTITUTE partial into the parent's queue
        ("send", "AggFlush"): "pausing",
        ("send", "PartialAggregate"): "pausing",
        ("send", "Start"): "starting",   # next invocation / cluster
        ("send", "Stop"): "stopped",
    },
    "stopped": {                        # stragglers drain silently
        ("send", "Stop"): "stopped",
        ("recv", "Register"): "stopped",
        ("recv", "Notify"): "stopped",
        ("recv", "Update"): "stopped",
        ("recv", "PartialAggregate"): "stopped",
    },
}

#: the aggregator tree's interior node (runtime/aggregate.py
#: L1Aggregator): drains its group's Updates, publishes ONE
#: PartialAggregate, exits.  Late member Updates draining after the
#: flush are legal (they are dropped as stale, but the consume itself
#: is not a protocol violation).  Each round spawns a FRESH
#: L1Aggregator instance under the SAME participant name
#: (``aggregator_{cluster}_{group}``), so a merged-log replay sees one
#: send per round from one name — the ``flushed`` send self-loop is
#: that round boundary, not a double-flush allowance (the validator
#: cannot see instance boundaries, so a true within-round double
#: publish is guarded by L1Aggregator.run's publish-then-return
#: structure instead).
AGGREGATOR_FSM: dict[str, dict[tuple[str, str], str]] = {
    "idle": {
        ("recv", "Update"): "idle",
        ("send", "PartialAggregate"): "flushed",
        # remote aggregator node (runtime/aggnode.py): adoption hello,
        # per-round assignment, child partials at interior levels
        ("send", "AggHello"): "idle",
        ("recv", "AggAssign"): "idle",
        ("recv", "AggFlush"): "idle",
        ("recv", "PartialAggregate"): "idle",
        ("recv", "Stop"): "stopped",
    },
    "flushed": {
        ("recv", "Update"): "flushed",
        ("send", "PartialAggregate"): "flushed",
        ("send", "AggHello"): "flushed",
        # the next invocation's assignment re-arms the node
        ("recv", "AggAssign"): "idle",
        ("recv", "AggFlush"): "flushed",
        ("recv", "PartialAggregate"): "flushed",
        ("recv", "Stop"): "stopped",
    },
    "stopped": {
        ("recv", "Stop"): "stopped",
    },
}

#: the MPMD stage host (runtime/stagehost.py StageHost): hello until
#: adopted, then a flat assignment loop — a StageAssign may arrive at
#: any time (initial fan-out, or a MID-ROUND re-assignment absorbing a
#: dead peer's slots), each spinning inner clients whose own protocol
#: traffic is validated under the client FSM.
STAGEHOST_FSM: dict[str, dict[tuple[str, str], str]] = {
    "idle": {
        ("send", "StageHello"): "idle",   # re-sent until adopted
        ("recv", "StageAssign"): "assigned",
        ("recv", "Stop"): "stopped",
    },
    "assigned": {
        ("send", "StageHello"): "assigned",   # reconnect re-hello
        ("recv", "StageAssign"): "assigned",  # re-assignment / top-up
        ("recv", "Stop"): "stopped",
    },
    "stopped": {
        ("recv", "Stop"): "stopped",
    },
}

CLIENT_FSM: dict[str, dict[tuple[str, str], str]] = {
    "idle": {
        ("send", "Register"): "idle",    # re-REGISTER until STARTed
        ("recv", "Start"): "started",
        ("recv", "Stop"): "stopped",
    },
    "started": {                        # shard built, data loaded
        ("send", "Ready"): "ready",
        ("recv", "Stop"): "stopped",
    },
    "ready": {
        ("recv", "Syn"): "training",
        ("recv", "Start"): "started",    # server re-STARTed the round
        ("recv", "Stop"): "stopped",
    },
    "training": {
        ("send", "Notify"): "notified",  # stage-1 data exhausted
        ("recv", "Pause"): "updating",   # middle/last stages skip NOTIFY
        # async pipelined rounds: a mid-round START makes the client
        # UPLOAD its work (an Update at the OLD version — the server's
        # admission window folds it staleness-weighted) before swapping
        # to the buffered seed; the requeued Start is consumed next.
        # Update may therefore be sent from training/notified without a
        # PAUSE having arrived.
        ("send", "Update"): "after_update",
        ("recv", "Start"): "started",    # timed out of the round; rejoin
        ("recv", "Stop"): "stopped",
    },
    "notified": {
        ("recv", "Pause"): "updating",
        ("send", "Update"): "after_update",  # async mid-round START
        ("recv", "Start"): "started",
        ("recv", "Stop"): "stopped",
    },
    "updating": {
        ("send", "Update"): "after_update",
        ("recv", "Stop"): "stopped",
    },
    "after_update": {
        ("recv", "Start"): "started",    # next round
        ("recv", "Stop"): "stopped",
    },
    "stopped": {
        ("recv", "Stop"): "stopped",
    },
}

# Heartbeats are lifecycle-orthogonal by design: a background thread
# publishes them at a fixed interval whatever state the lifecycle loop
# is in, and the server's pump consumes them in every state — so every
# state carries a Heartbeat self-loop rather than the message gating
# any transition (runtime/telemetry.py).
for _state, _transitions in SERVER_FSM.items():
    _transitions[("recv", "Heartbeat")] = _state
    # AggHello is lifecycle-orthogonal too: a node process may start
    # (or reconnect-and-rehello) at any point of the server's round
    _transitions[("recv", "AggHello")] = _state
    # FleetDigest frames arrive on the node's interval clock, whatever
    # round phase the server is in; DigestRoute re-points (digest-node
    # death fallback) happen the moment the death is noticed
    _transitions[("recv", "FleetDigest")] = _state
    _transitions[("send", "DigestRoute")] = _state
    # stage-host adoption/assignment is lifecycle-orthogonal the same
    # way: a host may hello at any point, and a mid-round host death
    # triggers an immediate re-assignment, whatever the round phase
    _transitions[("recv", "StageHello")] = _state
    _transitions[("send", "StageAssign")] = _state
    # flight-recorder snapshots fan out the moment a death is noticed,
    # whatever round phase the server is in (runtime/blackbox.py)
    _transitions[("send", "BlackboxDump")] = _state
for _state, _transitions in CLIENT_FSM.items():
    _transitions[("send", "Heartbeat")] = _state
    # heartbeat re-route is lifecycle-orthogonal: the beat thread's
    # target changes, the training lifecycle doesn't notice
    _transitions[("recv", "DigestRoute")] = _state
    # a fleet-snapshot request flushes the local blackbox ring and
    # nothing else — the training lifecycle doesn't notice
    _transitions[("recv", "BlackboxDump")] = _state
for _state, _transitions in AGGREGATOR_FSM.items():
    # remote nodes heartbeat from a background thread, any state; the
    # digest worker consumes routed clients' beats and publishes
    # merged digests on its own interval clock the same way
    _transitions[("send", "Heartbeat")] = _state
    _transitions[("recv", "Heartbeat")] = _state
    _transitions[("send", "FleetDigest")] = _state
    _transitions[("recv", "BlackboxDump")] = _state
for _state, _transitions in STAGEHOST_FSM.items():
    # stage hosts heartbeat from a background thread like clients
    _transitions[("send", "Heartbeat")] = _state
    _transitions[("recv", "BlackboxDump")] = _state

FSM_BY_ROLE = {"server": SERVER_FSM, "client": CLIENT_FSM,
               "aggregator": AGGREGATOR_FSM,
               "stagehost": STAGEHOST_FSM}
INITIAL_STATE = "idle"


@dataclasses.dataclass
class Event:
    """One protocol-visible action of one participant."""
    role: str            # "server" | "client"
    direction: str       # "send" | "recv"
    kind: str            # message class name
    participant: str = ""
    line: int = 0        # source line in the replayed log, if any


def validate_events(events: list[Event],
                    source: str = "<trace>") -> list[Finding]:
    """Replay per-participant event streams through the role FSMs.

    Illegal transitions are flagged and the state left unchanged
    (forgiving recovery: one bad event should not cascade into flagging
    the whole tail of the trace)."""
    findings: list[Finding] = []
    states: dict[str, str] = {}
    for ev in events:
        fsm = FSM_BY_ROLE.get(ev.role)
        if fsm is None or ev.kind not in ALL_KINDS:
            findings.append(Finding(
                "TV002", source, ev.line,
                ev.participant or ev.role,
                f"unknown role/kind in trace: {ev.role} "
                f"{ev.direction} {ev.kind}"))
            continue
        who = ev.participant or ev.role
        state = states.get(who, INITIAL_STATE)
        nxt = fsm[state].get((ev.direction, ev.kind))
        if nxt is None:
            legal = ", ".join(f"{d} {k}" for d, k in fsm[state])
            findings.append(Finding(
                "TV001", source, ev.line, who,
                f"illegal transition: {ev.direction} {ev.kind} in "
                f"state {state!r} (legal: {legal})"))
            continue
        states[who] = nxt
    return findings


# -- log replay -------------------------------------------------------------
# runtime/log.py writes "%(asctime)s - %(name)s - %(levelname)s -
# %(message)s" with [>>>] (sent) / [<<<] (received) markers; the logger
# name is "{participant}.{id:x}".  One app.log may interleave every
# participant of an in-process cell — events are split per participant
# and validated independently.

_LOG_RE = re.compile(
    r" - (?P<name>[^ ]+) - \w+ - .*?\[(?P<dir>>>>|<<<)\] (?P<word>\w+)")
_WORD_TO_KIND = {k.upper(): k for k in ALL_KINDS}


def events_from_log(text: str) -> list[Event]:
    events: list[Event] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _LOG_RE.search(line)
        if m is None:
            continue
        kind = _WORD_TO_KIND.get(m.group("word").upper())
        if kind is None:
            continue   # non-protocol marker line
        participant = m.group("name").rsplit(".", 1)[0]
        role = ("server" if participant == "server"
                else "aggregator" if participant.startswith("aggregator_")
                else "stagehost" if participant.startswith("stage_host")
                else "client")
        events.append(Event(
            role=role,
            direction="send" if m.group("dir") == ">>>" else "recv",
            kind=kind, participant=participant, line=lineno))
    return events


def validate_log(text: str, source: str = "app.log") -> list[Finding]:
    """Validate every participant's control-plane sequence in one
    (possibly interleaved) ``app.log``."""
    return validate_events(events_from_log(text), source=source)


# -- data-plane stream validation -------------------------------------------

def validate_data_stream(messages: list, queue: str,
                         source: str = "<stream>") -> list[Finding]:
    """Validate a decoded post-transport message stream on one data
    queue: only kinds legal for the queue family, no duplicate
    ``data_id`` delivery (the reliable layer's dedup contract), and no
    round regression (a message from round N after round N+1 means a
    stale frame leaked through the fences)."""
    findings: list[Finding] = []
    fam = queue_family(queue)
    legal = DATA_RULES.get(fam or "", frozenset())
    seen_ids: set = set()
    max_round = None
    for i, msg in enumerate(messages):
        kind = type(msg).__name__
        if kind not in legal:
            findings.append(Finding(
                "TV003", source, i + 1, queue,
                f"{kind} is not legal on {fam or 'unknown'} queue "
                f"{queue!r} (legal: {sorted(legal)})"))
            continue
        data_id = getattr(msg, "data_id", None)
        if data_id is not None:
            if (kind, data_id) in seen_ids:
                findings.append(Finding(
                    "TV003", source, i + 1, queue,
                    f"duplicate {kind} data_id={data_id!r} delivered"))
            seen_ids.add((kind, data_id))
        r = getattr(msg, "round_idx", None)
        if r is not None:
            if max_round is not None and r < max_round:
                findings.append(Finding(
                    "TV003", source, i + 1, queue,
                    f"round regression: {kind} round_idx={r} after "
                    f"round {max_round}"))
            max_round = r if max_round is None else max(max_round, r)
    return findings
