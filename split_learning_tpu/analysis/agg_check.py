"""``agg`` analyzer — server aggregation-path memory discipline.

**AG001**: no accumulation of per-client full parameter trees in the
server's round paths.  The streaming aggregation plane
(``runtime/aggregate.py``) exists so the UPDATE barrier holds O(1)
trees; a round path that quietly rebuilds a list/dict of per-client
``Update.params``/``batch_stats`` trees reintroduces the O(clients)
wall this plane removed — usually as an innocent-looking
comprehension feeding an aggregate call.

Flagged shapes (in ``runtime/server.py``, ``runtime/strategies.py``,
``runtime/loop.py``):

* a list/set/generator/dict comprehension whose ELEMENT expression
  extracts ``.params`` / ``.batch_stats`` (``[u.params for u in ups]``)
  — presence checks in the ``if`` clause are fine;
* ``something.append(<expr containing .params/.batch_stats>)``;
* a subscript store of such an expression
  (``store[u.client_id] = u.params``).

Escapes (trailing ``# slcheck: ...`` annotations):

* ``agg-oracle`` — the reference barrier fold the streaming plane is
  bit-compared against (kept deliberately, as the oracle);
* ``agg-state`` — deliberate bounded per-client persistence that IS a
  strategy's semantics (e.g. FLEX's client-level weight persistence).
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding
from split_learning_tpu.analysis.protocol_check import _annotations

#: server round-path files held to the no-accumulation rule
FILES = ("split_learning_tpu/runtime/server.py",
         "split_learning_tpu/runtime/strategies.py",
         "split_learning_tpu/runtime/loop.py")

#: Update attributes that carry a full per-client tree
TREE_ATTRS = frozenset({"params", "batch_stats"})

_ALLOW = ("agg-oracle", "agg-state")


def _extracts_tree(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr in TREE_ATTRS
               for n in ast.walk(node))


def check_source(source: str, rel: str) -> list[Finding]:
    tree = ast.parse(source)
    notes = _annotations(source)

    def allowed(lineno: int) -> bool:
        note = notes.get(lineno, "")
        return any(a in note for a in _ALLOW)

    findings: list[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        if not allowed(node.lineno):
            findings.append(Finding(
                "AG001", rel, node.lineno, "",
                f"{what} accumulates per-client full parameter trees "
                "in a server round path — fold incrementally "
                "(runtime/aggregate.py StreamingFold / ops/fedavg.py "
                "TreeFold) or annotate '# slcheck: agg-oracle' / "
                "'agg-state'"))

    for node in ast.walk(tree):
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            if _extracts_tree(node.elt):
                flag(node, "comprehension")
        elif isinstance(node, ast.DictComp):
            if _extracts_tree(node.value):
                flag(node, "dict comprehension")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "append":
            if any(_extracts_tree(a) for a in node.args):
                flag(node, "append")
        elif isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Subscript)
                        for t in node.targets):
            if _extracts_tree(node.value):
                flag(node, "subscript store")
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel in FILES:
        path = root / rel
        if path.exists():
            findings += check_source(path.read_text(), rel)
    return findings
