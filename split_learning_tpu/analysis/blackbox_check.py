"""``blackbox`` analyzer — flight-recorder coverage rules.

**BB001**: every process entry point must arm the flight recorder.
The whole value of ``runtime/blackbox.py`` is that *every* process in
the fleet carries a ring and abnormal-exit handlers — the postmortem
assembler names a victim from the survivors' dumps, so one uncovered
process is a hole in the causal timeline.  Rule: each entry-point
module in :data:`ENTRY_FILES` (the ``main()``s behind
``python -m split_learning_tpu.run/server/client/aggregator/stagehost/
broker``) must call ``blackbox.install`` / ``install_basic`` /
``configure`` somewhere in the module, or carry an explicit
``# slcheck: no-blackbox`` opt-out comment.

**BB002**: no silent swallow-and-continue on the transport hot path.
In :data:`HOT_FILES` an ``except``/``except Exception`` handler that
neither re-raises nor leaves any evidence — a fault-counter ``inc``, a
ring ``record``/``dump``, or at least a log ``warning``/``error`` — is
a fault that happened and left no trace for the postmortem to find.
Rule: such handlers must contain one of those calls (anywhere in the
handler) or carry ``# slcheck: no-blackbox`` on the ``except`` line
(reserved for teardown paths where the process is already unwinding).
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding

#: modules whose main() is a fleet process entry point
ENTRY_FILES = (
    "split_learning_tpu/run.py",
    "split_learning_tpu/runtime/server.py",
    "split_learning_tpu/runtime/client.py",
    "split_learning_tpu/runtime/aggnode.py",
    "split_learning_tpu/runtime/stagehost.py",
    "split_learning_tpu/broker.py",
)

#: transport hot-path files held to the no-silent-swallow rule
HOT_FILES = (
    "split_learning_tpu/runtime/bus.py",
    "split_learning_tpu/runtime/chaos.py",
)

OPT_OUT = "slcheck: no-blackbox"

#: call names that count as blackbox arming (BB001)
_INSTALL_NAMES = ("install", "install_basic", "configure",
                  "configure_basic")

#: call attr/names that count as evidence from an except handler (BB002)
_EVIDENCE_NAMES = ("inc", "record", "dump", "warning", "error",
                   "exception")


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _installs_blackbox(tree: ast.AST) -> bool:
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) \
                and _call_name(n) in _INSTALL_NAMES:
            f = n.func
            # require the blackbox module as the receiver so an
            # unrelated .install() can't satisfy the rule
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "blackbox":
                return True
    return False


def _leaves_evidence(handler: ast.ExceptHandler) -> bool:
    for n in ast.walk(handler):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call) \
                and _call_name(n) in _EVIDENCE_NAMES:
            return True
    return False


def _broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _opted_out(lines: list[str], lineno: int) -> bool:
    # the opt-out comment may ride the except line or the line above
    for ln in (lineno - 1, lineno - 2):
        if 0 <= ln < len(lines) and OPT_OUT in lines[ln]:
            return True
    return False


def check_entry(source: str, rel: str) -> list[Finding]:
    if OPT_OUT in source:
        return []
    tree = ast.parse(source)
    if _installs_blackbox(tree):
        return []
    line = 1
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n.name == "main":
            line = n.lineno
            break
    return [Finding(
        code="BB001", path=rel, line=line, where="main",
        message=("process entry point does not arm the flight "
                 "recorder: call blackbox.install(cfg, participant) "
                 "(or install_basic for config-less processes) so "
                 "this process dumps blackbox-*.json on abnormal "
                 "exit — or opt out with '# slcheck: no-blackbox'"))]


def check_hot(source: str, rel: str) -> list[Finding]:
    findings: list[Finding] = []
    lines = source.splitlines()
    tree = ast.parse(source)
    for n in ast.walk(tree):
        if not isinstance(n, ast.ExceptHandler):
            continue
        if not _broad(n):
            continue
        if _leaves_evidence(n):
            continue
        if _opted_out(lines, n.lineno):
            continue
        findings.append(Finding(
            code="BB002", path=rel, line=n.lineno, where="except",
            message=("broad except swallows a hot-path fault without "
                     "evidence: record it (faults.inc / "
                     "blackbox.record / log.warning) so the "
                     "postmortem can see it — or annotate the except "
                     "line with '# slcheck: no-blackbox' for teardown "
                     "paths")))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel in ENTRY_FILES:
        path = root / rel
        if path.exists():
            findings += check_entry(path.read_text(), rel)
    for rel in HOT_FILES:
        path = root / rel
        if path.exists():
            findings += check_hot(path.read_text(), rel)
    return findings
