"""Named lock factories with an opt-in runtime ordering check.

The transport stack (``runtime/bus.py``, ``runtime/chaos.py``) creates
its locks and condition variables through :func:`make_lock` /
:func:`make_condition` so every primitive carries a *rank name* from
:data:`LOCK_ORDER`.  By default the factories return plain
``threading`` primitives — zero overhead, byte-identical behavior.

With ``SLCHECK_LOCKS=1`` in the environment they return checked
wrappers that keep a per-thread stack of held ranks and raise
:class:`LockOrderViolation` the moment any thread acquires a lock whose
rank is not strictly inner to everything it already holds.  This is the
runtime twin of the static lock-order lint
(:mod:`split_learning_tpu.analysis.concurrency`): the lint proves the
order is consistent in the AST, the instrumented mode proves the same
order on a live run (tests enable it around transport exercises).

``LOCK_ORDER`` is outermost-first and mirrors the transport stack's
layering (``runtime/chaos.py make_runtime_transport``): AsyncTransport
wraps ReliableTransport wraps ChaosTransport wraps the base bus.  A
well-behaved layer never calls *into* an inner layer while holding its
own lock, so in a correct run the per-thread stack never holds more
than one rank at a time — the checker exists to catch the regression
that breaks that.
"""

from __future__ import annotations

import os
import threading

#: global acquisition order, outermost first.  A thread may only
#: acquire a lock whose rank appears STRICTLY LATER than every rank it
#: already holds.
LOCK_ORDER = (
    "async",            # AsyncTransport._lock/_cv (outermost wrapper)
    "prefetch",         # _Prefetcher._cond
    "reliable",         # ReliableTransport._lock
    "chaos",            # ChaosTransport._lock
    "tcp.shards",       # ShardedTcpTransport._shard_lock (shard map)
    "tcp.io",           # TcpTransport._lock (socket serialization)
    "inproc",           # InProcTransport._lock/_cond (base bus)
    "transport.count",  # Transport._count_lock (leaf byte counters)
)


class LockOrderViolation(AssertionError):
    """A thread acquired locks against :data:`LOCK_ORDER`."""


def _rank(name: str) -> int:
    try:
        return LOCK_ORDER.index(name)
    except ValueError:
        raise ValueError(f"unknown lock rank {name!r}; add it to "
                         "analysis.locks.LOCK_ORDER") from None


_held = threading.local()


def _stack() -> list:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = []
        setattr(_held, "stack", stack)  # noqa: B010 — dynamic TLS slot
    return stack


def _push(name: str) -> None:
    stack = _stack()
    rank = _rank(name)
    if stack and rank <= stack[-1][1]:
        held = ", ".join(n for n, _ in stack)
        raise LockOrderViolation(
            f"acquiring {name!r} while holding [{held}] violates "
            f"LOCK_ORDER {LOCK_ORDER}")
    stack.append((name, rank))


def _pop(name: str) -> None:
    stack = _stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            del stack[i]
            return


class _CheckedLock:
    """``threading.Lock`` facade that records rank on acquisition."""

    def __init__(self, name: str):
        self._slname = name
        self._real = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # order is asserted at REQUEST time, before blocking: a
        # violation must raise without leaving the raw lock held (and
        # checking before the wait is what prevents the deadlock the
        # order exists to rule out)
        _push(self._slname)
        ok = self._real.acquire(blocking, timeout)
        if not ok:
            _pop(self._slname)
        return ok

    def release(self) -> None:
        _pop(self._slname)
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class _CheckedCondition(threading.Condition):
    """Condition that records its lock's rank on ``with``-entry.

    ``wait``/``wait_for`` release and reacquire the underlying lock
    internally without touching the rank stack — the waiting thread
    still *logically* owns the region, and other threads are checked
    against their own per-thread stacks."""

    def __init__(self, name: str, lock=None):
        self._slname = name
        real = lock._real if isinstance(lock, _CheckedLock) else lock
        super().__init__(real)
        self._sllock = lock

    def __enter__(self):
        _push(self._slname)
        return super().__enter__()

    def __exit__(self, *exc):
        _pop(self._slname)
        return super().__exit__(*exc)


def checking_enabled() -> bool:
    return os.environ.get("SLCHECK_LOCKS", "") not in ("", "0")


def make_lock(name: str):
    """A lock carrying rank ``name`` (plain ``threading.Lock`` unless
    ``SLCHECK_LOCKS=1``)."""
    if checking_enabled():
        return _CheckedLock(name)
    return threading.Lock()


def make_condition(name: str, lock=None):
    """A condition variable carrying rank ``name``.  ``lock`` may be a
    lock from :func:`make_lock` to share its underlying primitive (the
    aliasing ``Condition(self._lock)`` pattern)."""
    if checking_enabled():
        return _CheckedCondition(name, lock)
    if isinstance(lock, _CheckedLock):  # mixed-mode construction
        lock = lock._real
    return threading.Condition(lock)
