"""``slcheck`` — static analysis for the split-learning runtime.

Three analyzers over the three subsystems whose invariants used to be
enforced only by runtime tests:

* :mod:`~split_learning_tpu.analysis.protocol_check` — the wire
  protocol: every send/recv site against the declarative message
  state machine in :mod:`~split_learning_tpu.analysis.model`, codec
  coverage (encode/decode/crc/chaos-injection) for every frame kind;
* :mod:`~split_learning_tpu.analysis.jaxpr_audit` — the compiled hot
  path: host syncs in tick loops, fp32 upcasts on the bf16 wire,
  recompile hazards, donated-buffer reuse;
* :mod:`~split_learning_tpu.analysis.concurrency` — the transport
  threads: lock ordering, blocking-under-lock, thread shutdown paths
  (with a runtime twin in :mod:`~split_learning_tpu.analysis.locks`,
  ``SLCHECK_LOCKS=1``);
* :mod:`~split_learning_tpu.analysis.codec_check` — the wire codecs:
  every codec counter registered, no host-side quantization in hot
  loops, quantizer kernels actually staged on device;
* :mod:`~split_learning_tpu.analysis.pallas_check` — the Pallas
  kernel plane (PK001): every enableable kernel (fused quantize/
  dequantize, fused stage_update, llama flash attention) traced with
  the kernel on must show its ``pallas_call`` in the hot-path jaxpr —
  kernels cannot silently fall back to XLA.

CLI: ``python -m split_learning_tpu.analysis`` (wrapper:
``tools/slcheck.py``).  This package is import-light on purpose —
``runtime/bus.py`` imports :mod:`~split_learning_tpu.analysis.locks`
at startup, so nothing here may pull in jax at module scope.
"""

from __future__ import annotations

__all__ = ["run_analyzers", "ANALYZERS"]


def __getattr__(name):
    if name in __all__:
        from split_learning_tpu.analysis import __main__ as _cli
        return getattr(_cli, name)
    raise AttributeError(name)
