"""``python -m split_learning_tpu.analysis`` — the slcheck CLI.

Runs the analyzers (protocol conformance, jaxpr hot-path audit,
concurrency lint, counter-name registry) over the repo, subtracts the
checked-in suppression baseline, and reports the rest.  Exit code 1 iff
any non-baselined finding remains, so it slots straight into CI.

    python -m split_learning_tpu.analysis                 # human output
    python -m split_learning_tpu.analysis --format json   # machine
    python -m split_learning_tpu.analysis --analyzers protocol,concurrency
    python -m split_learning_tpu.analysis --no-trace      # AST-only (no jax)
    python -m split_learning_tpu.analysis --validate-log app.log
    python -m split_learning_tpu.analysis --write-baseline  # accept debt
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from split_learning_tpu.analysis.findings import (
    Baseline, Finding, render_human, render_json,
)

ANALYZERS = ("protocol", "jaxpr", "concurrency", "counters", "codec",
             "perf", "agg", "async", "sched", "pallas", "blackbox")


def repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def run_analyzers(root: pathlib.Path, names=ANALYZERS,
                  trace: bool = True) -> list[Finding]:
    findings: list[Finding] = []
    if "protocol" in names:
        from split_learning_tpu.analysis import protocol_check
        findings += protocol_check.run(root)
    if "jaxpr" in names:
        from split_learning_tpu.analysis import jaxpr_audit
        findings += jaxpr_audit.run(root, trace=trace)
    if "concurrency" in names:
        from split_learning_tpu.analysis import concurrency
        findings += concurrency.run(root)
    if "counters" in names:
        from split_learning_tpu.analysis import counters
        findings += counters.run(root)
    if "codec" in names:
        from split_learning_tpu.analysis import codec_check
        findings += codec_check.run(root, trace=trace)
    if "perf" in names:
        from split_learning_tpu.analysis import perf_check
        findings += perf_check.run(root)
    if "agg" in names:
        from split_learning_tpu.analysis import agg_check
        findings += agg_check.run(root)
    if "async" in names:
        from split_learning_tpu.analysis import async_check
        findings += async_check.run(root)
    if "sched" in names:
        from split_learning_tpu.analysis import sched_check
        findings += sched_check.run(root)
    if "pallas" in names:
        from split_learning_tpu.analysis import pallas_check
        findings += pallas_check.run(root, trace=trace)
    if "blackbox" in names:
        from split_learning_tpu.analysis import blackbox_check
        findings += blackbox_check.run(root)
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="slcheck",
        description="Static analysis for the wire protocol, the "
                    "compiled hot path and the transport threads.")
    ap.add_argument("--format", choices=("human", "json"),
                    default="human")
    ap.add_argument("--analyzers", default=",".join(ANALYZERS),
                    help="comma-separated subset of "
                         f"{'/'.join(ANALYZERS)}")
    ap.add_argument("--root", default=None,
                    help="repo root (default: derived from the package)")
    ap.add_argument("--baseline", default=None,
                    help="suppression file (default: "
                         "tools/slcheck_baseline.json under the root)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept every current finding into the "
                         "baseline instead of failing")
    ap.add_argument("--no-trace", action="store_true",
                    help="skip the jaxpr tracing pass (no jax import; "
                         "AST checks still run)")
    ap.add_argument("--validate-log", default=None, metavar="PATH",
                    help="additionally replay a recorded app.log "
                         "through the protocol-model trace validator")
    args = ap.parse_args(argv)

    root = pathlib.Path(args.root) if args.root else repo_root()
    names = tuple(n.strip() for n in args.analyzers.split(",") if n)
    for n in names:
        if n not in ANALYZERS:
            ap.error(f"unknown analyzer {n!r}")
    findings = run_analyzers(root, names, trace=not args.no_trace)

    if args.validate_log:
        from split_learning_tpu.analysis.model import validate_log
        text = pathlib.Path(args.validate_log).read_text()
        findings += validate_log(text, source=args.validate_log)

    baseline_path = (pathlib.Path(args.baseline) if args.baseline
                     else root / "tools" / "slcheck_baseline.json")
    baseline = Baseline.load(baseline_path)
    if args.write_baseline:
        # only a FULL run may prune: a partial analyzer set must not
        # delete the other analyzers' accepted suppressions
        full_run = set(names) == set(ANALYZERS) and not args.no_trace
        baseline.save(findings, prune=full_run)
        print(f"wrote {len(findings)} suppression(s) to "
              f"{baseline_path}"
              + ("" if full_run else " (partial run: existing "
                 "suppressions kept)"))
        return 0
    new, suppressed = baseline.split(findings)
    out = (render_json(new, suppressed) if args.format == "json"
           else render_human(new, suppressed))
    print(out)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
