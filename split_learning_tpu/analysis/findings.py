"""Finding records, suppression baseline, and report rendering.

Every analyzer emits :class:`Finding` rows.  A finding's *fingerprint*
deliberately excludes the line number — suppressions must survive
unrelated edits above the flagged site — and is built from the analyzer
code, the repo-relative path, and a stable detail slug (usually the
enclosing function or the flagged symbol).

The checked-in baseline (``tools/slcheck_baseline.json``) lists
fingerprints for accepted debt so ``slcheck`` can gate CI on *new*
findings only.  Format::

    {"version": 1,
     "suppressions": [{"fingerprint": "CL002:runtime/bus.py:get",
                       "reason": "why this is accepted"}]}
"""

from __future__ import annotations

import dataclasses
import json
import pathlib


@dataclasses.dataclass
class Finding:
    code: str           # e.g. "PC001"
    path: str           # repo-relative source path (or "<trace>")
    line: int           # 1-based, 0 when not tied to a source line
    where: str          # stable slug: enclosing function / symbol
    message: str

    @property
    def fingerprint(self) -> str:
        return f"{self.code}:{self.path}:{self.where}"

    def to_dict(self) -> dict:
        return {"code": self.code, "path": self.path, "line": self.line,
                "where": self.where, "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{self.code} {loc} [{self.where}] {self.message}"


class Baseline:
    """Suppression set keyed by fingerprint."""

    def __init__(self, suppressions: dict[str, str] | None = None,
                 path: pathlib.Path | None = None):
        self.suppressions: dict[str, str] = dict(suppressions or {})
        self.path = path

    @classmethod
    def load(cls, path: pathlib.Path) -> "Baseline":
        if not path.exists():
            return cls(path=path)
        data = json.loads(path.read_text())
        sups = {s["fingerprint"]: s.get("reason", "")
                for s in data.get("suppressions", [])}
        return cls(sups, path=path)

    def save(self, findings: list[Finding], prune: bool = True) -> None:
        """Persist ``findings`` as suppressions.  ``prune=False`` keeps
        every existing suppression too — required when only a SUBSET of
        analyzers ran (a partial run must not delete other analyzers'
        accepted debt); a full run prunes entries that no longer
        fire."""
        assert self.path is not None
        merged = {} if prune else dict(self.suppressions)
        for f in findings:
            merged[f.fingerprint] = self.suppressions.get(
                f.fingerprint, "baselined by --write-baseline")
        # stable order so the checked-in file diffs cleanly
        sups = [{"fingerprint": fp, "reason": reason}
                for fp, reason in sorted(merged.items())]
        self.path.write_text(json.dumps(
            {"version": 1, "suppressions": sups}, indent=2) + "\n")

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding]]:
        """(new, suppressed) partition of ``findings``."""
        new, sup = [], []
        for f in findings:
            (sup if f.fingerprint in self.suppressions else new).append(f)
        return new, sup


def render_human(new: list[Finding], suppressed: list[Finding]) -> str:
    lines = []
    for f in new:
        lines.append(f.render())
    if suppressed:
        lines.append(f"({len(suppressed)} baselined finding(s) "
                     "suppressed)")
    if not new:
        lines.append("slcheck: clean")
    else:
        lines.append(f"slcheck: {len(new)} new finding(s)")
    return "\n".join(lines)


def render_json(new: list[Finding], suppressed: list[Finding]) -> str:
    return json.dumps({
        "ok": not new,
        "findings": [f.to_dict() for f in new],
        "suppressed": [f.to_dict() for f in suppressed],
    }, indent=2)
