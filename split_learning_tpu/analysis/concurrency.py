"""Concurrency lint for the transport threads.

Builds the lock/thread graph of ``runtime/bus.py``, ``runtime/chaos.py``
and ``broker.py`` from the AST — every ``threading.Lock`` /
``Condition`` / ``Thread`` / ``Timer`` site (including ones created via
the rank-named factories in :mod:`split_learning_tpu.analysis.locks`)
— and checks:

* **CL001** — lock acquisition order is globally consistent: the
  held-lock -> acquired-lock nesting graph (direct nested ``with``
  plus transitive same-class method calls) must be acyclic, and no
  path may re-acquire a lock already held (non-reentrant deadlock);
* **CL002** — no blocking call (socket I/O, ``time.sleep``, ``join``,
  frame send/recv helpers) runs while a state lock is held.  A lock
  whose assignment carries ``# slcheck: io-lock`` is exempt — it
  exists to serialize an I/O resource (TcpTransport's single socket)
  and blocking under it is its purpose.  ``cond.wait``/``wait_for``
  under its own condition is always legal (it releases the lock);
* **CL003** — every started thread/timer has a join/cancel shutdown
  path in its owning class (direct ``attr.join()`` or a loop over the
  list the thread is registered in);
* **CL004** — ``wait``/``wait_for``/``notify``/``notify_all`` on a
  condition only ever run inside a ``with`` of that same condition;
* **CL005** — no call into the inner/wrapped transport
  (``self.inner`` / ``self._side`` / ``self.src`` / ``self._store``)
  while holding one's own state lock: the wrapper layering is the
  cross-class lock order, and calling down while holding up is how
  lock-order inversions between layers are born.

The runtime twin of CL001 is the instrumented-lock mode
(``SLCHECK_LOCKS=1``, :mod:`split_learning_tpu.analysis.locks`).
"""

from __future__ import annotations

import ast
import pathlib
import re

from split_learning_tpu.analysis.findings import Finding

FILES = ("split_learning_tpu/runtime/bus.py",
         "split_learning_tpu/runtime/chaos.py",
         "split_learning_tpu/broker.py")

_LOCK_CTORS = {"Lock", "RLock", "Condition", "make_lock",
               "make_condition"}
_THREAD_CTORS = {"Thread", "Timer"}
_BLOCKING_ATTRS = {"sleep", "join", "recv", "sendall", "sendto",
                   "accept", "connect", "create_connection", "flush",
                   "result", "block_until_ready", "device_get"}
_INNER_OBJECTS = {"inner", "_side", "src", "_store"}
_ANNOT_RE = re.compile(r"#\s*slcheck:\s*(.+?)\s*$")


def _ctor_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return None


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, module: str, node: ast.ClassDef,
                 source_lines: list[str]):
        self.module = module
        self.name = node.name
        self.node = node
        self.methods = {m.name: m for m in node.body
                        if isinstance(m, ast.FunctionDef)}
        # lock attrs: attr -> {"kind", "io", "alias"}
        self.locks: dict[str, dict] = {}
        # thread attrs + list-registered threads
        self.thread_attrs: dict[str, int] = {}
        self.thread_lists: dict[str, int] = {}
        for m in self.methods.values():
            for stmt in ast.walk(m):
                if not isinstance(stmt, ast.Assign):
                    continue
                ctor = _ctor_name(stmt.value)
                tgt = (stmt.targets[0] if len(stmt.targets) == 1
                       else None)
                attr = _self_attr(tgt) if tgt is not None else None
                local = (tgt.id if isinstance(tgt, ast.Name) else None)
                if ctor in _LOCK_CTORS and attr:
                    line = source_lines[stmt.lineno - 1] \
                        if stmt.lineno <= len(source_lines) else ""
                    note = _ANNOT_RE.search(line)
                    alias = None
                    call = stmt.value
                    if isinstance(call, ast.Call):
                        for a in call.args:
                            sub = _self_attr(a)
                            if sub:   # Condition(self._lock) aliasing
                                alias = sub
                    self.locks[attr] = {
                        "kind": ctor,
                        "io": bool(note and "io-lock" in note.group(1)),
                        "alias": alias,
                        "line": stmt.lineno,
                    }
                elif ctor in _THREAD_CTORS:
                    if attr:
                        self.thread_attrs[attr] = stmt.lineno
                    elif local is not None:
                        # registered into a list attr?
                        reg = None
                        for sub in ast.walk(m):
                            if (isinstance(sub, ast.Call)
                                    and isinstance(sub.func,
                                                   ast.Attribute)
                                    and sub.func.attr == "append"
                                    and sub.args
                                    and isinstance(sub.args[0],
                                                   ast.Name)
                                    and sub.args[0].id == local):
                                reg = _self_attr(sub.func.value)
                        if reg:
                            self.thread_lists[reg] = stmt.lineno
                        else:
                            self.thread_attrs[f"<local {local}>"] = \
                                stmt.lineno

    def canonical(self, attr: str) -> str:
        info = self.locks.get(attr)
        if info and info["alias"] and info["alias"] in self.locks:
            return info["alias"]
        return attr

    def is_lock(self, attr: str) -> bool:
        return attr in self.locks

    def is_io(self, attr: str) -> bool:
        info = self.locks.get(self.canonical(attr)) \
            or self.locks.get(attr)
        return bool(info and info["io"]) or bool(
            self.locks.get(attr, {}).get("io"))


def _method_lock_sets(cls: _ClassInfo, depth: int = 3
                      ) -> dict[str, set[str]]:
    """attr-canonical locks each method may acquire (transitive)."""
    cache: dict[str, set[str]] = {}

    def compute(name: str, seen: frozenset) -> set[str]:
        if name in cache:
            return cache[name]
        if name in seen or name not in cls.methods:
            return set()
        acquired: set[str] = set()
        for node in ast.walk(cls.methods[name]):
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr and cls.is_lock(attr):
                        acquired.add(cls.canonical(attr))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and _self_attr(f.value) is None \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" \
                        and f.attr in cls.methods:
                    if len(seen) < depth:
                        acquired |= compute(f.attr,
                                            seen | {name})
        cache[name] = acquired
        return acquired

    return {m: compute(m, frozenset()) for m in cls.methods}


def _module_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in tree.body
            if isinstance(n, ast.FunctionDef)}


def _blocks(node: ast.AST, mod_funcs: dict, seen: frozenset = frozenset()
            ) -> str | None:
    """Name of a blocking call reachable from ``node``, else None."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute) and f.attr in _BLOCKING_ATTRS:
            return ast.unparse(f)
        if isinstance(f, ast.Name) and f.id in mod_funcs \
                and f.id not in seen and len(seen) < 3:
            hit = _blocks(mod_funcs[f.id], mod_funcs, seen | {f.id})
            if hit:
                return f"{f.id} -> {hit}"
    return None


class _RegionChecker(ast.NodeVisitor):
    """Walks one method tracking the held-lock stack."""

    def __init__(self, cls: _ClassInfo, method: ast.FunctionDef,
                 mod_funcs: dict, rel: str,
                 findings: list[Finding]):
        self.cls = cls
        self.method = method
        self.mod_funcs = mod_funcs
        self.rel = rel
        self.findings = findings
        self.stack: list[str] = []       # canonical lock attrs held
        self.edges: set[tuple] = set()   # (held, acquired)

    def _where(self) -> str:
        return f"{self.cls.name}.{self.method.name}"

    def visit_With(self, node: ast.With):
        attrs = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr and self.cls.is_lock(attr):
                attrs.append(self.cls.canonical(attr))
        for attr in attrs:
            if attr in self.stack:
                self.findings.append(Finding(
                    "CL001", self.rel, node.lineno, self._where(),
                    f"re-acquires non-reentrant lock self.{attr} "
                    "already held on this path"))
            for held in self.stack:
                self.edges.add((f"{self.cls.name}.{held}",
                                f"{self.cls.name}.{attr}"))
            self.stack.append(attr)
        for stmt in node.body:
            self.visit(stmt)
        for attr in attrs:
            self.stack.pop()

    def visit_Call(self, node: ast.Call):
        if self.stack:
            held = self.stack[-1]
            # exemptions consider the WHOLE stack, not the innermost
            # lock: blocking inside `with io_lock:` nested under a
            # still-held state lock blocks the state lock just the same
            non_io = [a for a in self.stack
                      if not self.cls.is_io(a)]
            io_held = not non_io
            f = node.func
            # CL005: descending into the wrapped transport under a lock
            if isinstance(f, ast.Attribute):
                base = f.value
                base_attr = _self_attr(base)
                if base_attr in _INNER_OBJECTS and not io_held:
                    self.findings.append(Finding(
                        "CL005", self.rel, node.lineno, self._where(),
                        f"calls self.{base_attr}.{f.attr} while "
                        f"holding self.{non_io[-1]}: wrapper locks "
                        "must be released before descending a "
                        "transport layer"))
                # waiting on the innermost condition releases IT — but
                # any OUTER state lock stays held through the wait
                if f.attr in ("wait", "wait_for") \
                        and base_attr is not None \
                        and self.cls.canonical(base_attr) == held:
                    outer_non_io = [a for a in self.stack[:-1]
                                    if not self.cls.is_io(a)]
                    if outer_non_io:
                        self.findings.append(Finding(
                            "CL002", self.rel, node.lineno,
                            self._where(),
                            f"self.{base_attr}.{f.attr}() waits while "
                            f"outer lock self.{outer_non_io[-1]} stays "
                            "held"))
                    return
            # CL001 transitive: self-method that acquires locks
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self" \
                    and f.attr in self.cls.methods:
                for acq in self._lock_sets.get(f.attr, set()):
                    if acq in self.stack:
                        self.findings.append(Finding(
                            "CL001", self.rel, node.lineno,
                            self._where(),
                            f"self.{f.attr}() re-acquires held lock "
                            f"self.{acq}"))
                    for held2 in self.stack:
                        self.edges.add(
                            (f"{self.cls.name}.{held2}",
                             f"{self.cls.name}.{acq}"))
            # CL002: blocking work while any non-io lock is held
            if not io_held:
                hit = _blocks(node, self.mod_funcs)
                if hit:
                    self.findings.append(Finding(
                        "CL002", self.rel, node.lineno, self._where(),
                        f"blocking call {hit} while holding "
                        f"self.{non_io[-1]}"))
                    return   # one finding per call expression
        self.generic_visit(node)

    _lock_sets: dict[str, set[str]] = {}


def _check_cond_discipline(cls: _ClassInfo, rel: str,
                           findings: list[Finding]) -> None:
    conds = {a for a, info in cls.locks.items()
             if info["kind"] in ("Condition", "make_condition")}
    if not conds:
        return

    class V(ast.NodeVisitor):
        def __init__(self, method):
            self.method = method
            self.held: list[str] = []

        def visit_With(self, node):
            attrs = []
            for item in node.items:
                a = _self_attr(item.context_expr)
                if a:
                    attrs.append(a)
            self.held += attrs
            for stmt in node.body:
                self.visit(stmt)
            del self.held[len(self.held) - len(attrs):]

        def visit_Call(self, node):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in (
                    "wait", "wait_for", "notify", "notify_all"):
                attr = _self_attr(f.value)
                if attr in conds and attr not in self.held:
                    findings.append(Finding(
                        "CL004", rel, node.lineno,
                        f"{cls.name}.{self.method}",
                        f"self.{attr}.{f.attr}() outside 'with "
                        f"self.{attr}:'"))
            self.generic_visit(node)

    for name, m in cls.methods.items():
        V(name).visit(m)


def _check_threads(cls: _ClassInfo, rel: str,
                   findings: list[Finding]) -> None:
    src = ast.unparse(cls.node)
    for attr, lineno in cls.thread_attrs.items():
        if attr.startswith("<local"):
            findings.append(Finding(
                "CL003", rel, lineno, cls.name,
                f"thread {attr} is started but never registered for "
                "join/cancel"))
            continue
        if not re.search(rf"self\.{re.escape(attr)}\.(join|cancel)\(",
                         src):
            findings.append(Finding(
                "CL003", rel, lineno, cls.name,
                f"thread self.{attr} has no join/cancel shutdown "
                f"path in {cls.name}"))
    for lst, lineno in cls.thread_lists.items():
        joined = False
        for node in ast.walk(cls.node):
            if isinstance(node, ast.For) \
                    and lst in ast.unparse(node.iter):
                body_src = "\n".join(ast.unparse(s) for s in node.body)
                if ".join(" in body_src or ".cancel(" in body_src:
                    joined = True
        if not joined:
            findings.append(Finding(
                "CL003", rel, lineno, cls.name,
                f"threads registered in self.{lst} are never "
                "joined/cancelled"))


def _find_cycle(edges: set[tuple]) -> list | None:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(graph) | {b for bs in graph.values() for b in bs}}
    path: list[str] = []

    def dfs(n: str):
        color[n] = GRAY
        path.append(n)
        for m in graph.get(n, ()):
            if color[m] == GRAY:
                return path[path.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc:
                    return cyc
        path.pop()
        color[n] = BLACK
        return None

    for n in list(color):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc:
                return cyc
    return None


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    all_edges: set[tuple] = set()
    for rel in FILES:
        path = root / rel
        source = path.read_text()
        lines = source.splitlines()
        tree = ast.parse(source)
        mod_funcs = _module_functions(tree)
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            cls = _ClassInfo(rel, node, lines)
            lock_sets = _method_lock_sets(cls)
            for m in cls.methods.values():
                checker = _RegionChecker(cls, m, mod_funcs, rel,
                                         findings)
                checker._lock_sets = lock_sets
                checker.visit(m)
                all_edges |= checker.edges
            _check_cond_discipline(cls, rel, findings)
            _check_threads(cls, rel, findings)
    cycle = _find_cycle(all_edges)
    if cycle:
        findings.append(Finding(
            "CL001", FILES[0], 0, "lock-graph",
            "lock acquisition order is inconsistent: cycle "
            + " -> ".join(cycle)))
    return findings
