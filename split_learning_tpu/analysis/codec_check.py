"""Wire-codec conformance (CD001-CD003).

The codec layer (``runtime/codec/``) has three invariants that used to
live only in reviewers' heads:

* **CD001 — counters registered**: every counter a codec declares in
  ``codec/specs.py CODEC_COUNTERS`` must be a member of the declared
  registries in ``runtime/trace.py`` (the CT-rules' single source of
  truth).  A codec minting its own name would inc into a key no
  metrics consumer, dashboard or test ever reads.
* **CD002 — no host quantization in hot loops**: the data-plane codecs
  exist to move quantized bytes over PCIe, which only happens when the
  quantizer runs ON DEVICE before the fetch.  A call to a host-side
  quantizer (``_quant_int8``, ``quantize_np``) inside a ``for``/
  ``while`` body in the hot-path modules is the exact regression the
  device kernels were built to eliminate — the numpy twins are legal
  ONLY on the once-per-round Update/delta path, which has no loop.
* **CD003 — quantization actually on device** (jaxpr-flavored, needs
  jax): ``QuantCodec.prepare`` is traced with abstract inputs; its
  staged output must carry int8/uint8 code arrays (the fetch then
  moves quantized bytes).  A codec that silently fell back to a host
  path fails the trace (tracer leak) or ships float codes — both are
  findings, mirroring the JX002 wire-width audit.
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding

#: host-side quantizer entry points (the numpy twins + the legacy
#: per-tensor int8 helper); calling any of these under a loop in a
#: hot-path module is CD002
_HOST_QUANT_FNS = frozenset({"_quant_int8", "quantize_np"})

#: modules whose loops are the data-plane hot path
_HOT_MODULES = ("split_learning_tpu/runtime/client.py",)


def check_counters(registries=None, codec_counters=None) -> list[Finding]:
    """CD001 over the declared codec counter vocabulary."""
    if registries is None:
        from split_learning_tpu.runtime import trace
        registries = trace.FAULT_COUNTER_NAMES | trace.HISTOGRAM_NAMES
    if codec_counters is None:
        from split_learning_tpu.runtime.codec.specs import CODEC_COUNTERS
        codec_counters = CODEC_COUNTERS
    findings: list[Finding] = []
    rel = "split_learning_tpu/runtime/codec/specs.py"
    for kind, names in sorted(codec_counters.items()):
        for name in names:
            if name not in registries:
                findings.append(Finding(
                    "CD001", rel, 0, kind,
                    f"codec {kind!r} declares counter {name!r} which "
                    "is not registered in runtime/trace.py "
                    "FAULT_COUNTER_NAMES/HISTOGRAM_NAMES"))
    return findings


def scan_source(source: str, rel: str) -> list[Finding]:
    """CD002 over one hot-path source file."""
    findings: list[Finding] = []
    tree = ast.parse(source)
    fn_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                lineno = getattr(sub, "lineno", None)
                if lineno is not None:
                    fn_of.setdefault(lineno, node.name)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in _HOST_QUANT_FNS:
                findings.append(Finding(
                    "CD002", rel, sub.lineno,
                    fn_of.get(sub.lineno, name),
                    f"host-side quantizer {name}() called inside a "
                    "hot loop — quantize on device via the codec's "
                    "prepare() so the device->host fetch moves "
                    "quantized bytes"))
    return findings


def check_device_quant() -> list[Finding]:
    """CD003: trace each quantizer spec's prepare with abstract inputs."""
    import jax
    import jax.numpy as jnp

    from split_learning_tpu.runtime.codec.quant import QuantCodec
    from split_learning_tpu.runtime.codec.specs import parse_spec

    rel = "split_learning_tpu/runtime/codec/quant.py"
    findings: list[Finding] = []
    for spec in ("int8:64", "int4:64"):
        codec = QuantCodec(parse_spec(spec))
        x = jnp.zeros((4, 100), jnp.float32)
        try:
            staged = jax.eval_shape(lambda t, c=codec: c.prepare(t), x)
        except Exception as e:  # noqa: BLE001 — a tracer leak IS the
            # finding: prepare pulled the payload to host mid-trace
            findings.append(Finding(
                "CD003", rel, 0, spec,
                f"QuantCodec({spec}).prepare does not trace "
                f"device-side: {type(e).__name__}: {e}"))
            continue
        leaves = jax.tree_util.tree_leaves(staged)
        code_dtypes = {str(leaf.dtype) for leaf in leaves}
        if not code_dtypes & {"int8", "uint8"}:
            findings.append(Finding(
                "CD003", rel, 0, spec,
                f"QuantCodec({spec}).prepare stages {code_dtypes} — "
                "no int8/uint8 code array; the fetch would move "
                "unquantized bytes (quantize on device)"))
    return findings


def run(root: pathlib.Path, trace: bool = True) -> list[Finding]:
    findings = check_counters()
    for rel in _HOT_MODULES:
        path = root / rel
        try:
            source = path.read_text()
        except OSError:
            continue
        findings += scan_source(source, rel)
    if trace:
        findings += check_device_quant()
    return findings
