"""``sched`` analyzer — no silent scheduler control actions.

**SC001**: every scheduler decision site must emit a ``kind=sched``
journal record.  The closed-loop scheduler
(``runtime/scheduler.py``) concentrates its control actions in
``_act_*`` methods — evict, demote, re-plan, mid-round barrier drop,
cluster move — and each one must call ``self.journal(...)`` (the one
funnel that writes the ``kind=sched`` metrics record and the bounded
in-memory journal ``/fleet`` serves).  A decision site that skips the
journal is a control action an operator can never attribute: a client
disappears from the round and nothing on disk says why.  That is
exactly the debuggability regression this rule exists to prevent —
the scheduler is allowed to act only on the record.

Rule: in ``runtime/scheduler.py``, every function whose name starts
with ``_act_`` must contain a call whose attribute name is
``journal``.  The prefix is the extension point: new control actions
are added as ``_act_*`` methods and inherit the obligation
automatically (a reviewer adding a decision path outside an ``_act_*``
method will meet the convention in the module docstring and this
analyzer's tests).
"""

from __future__ import annotations

import ast
import pathlib

from split_learning_tpu.analysis.findings import Finding

#: files holding scheduler decision sites
FILES = ("split_learning_tpu/runtime/scheduler.py",)

#: decision-site naming convention
ACT_PREFIX = "_act_"


def _calls_journal(fn: ast.AST) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "journal":
                return True
            if isinstance(f, ast.Name) and f.id == "journal":
                return True
    return False


def check_source(source: str, rel: str) -> list[Finding]:
    findings: list[Finding] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith(ACT_PREFIX):
            continue
        if not _calls_journal(node):
            findings.append(Finding(
                code="SC001", path=rel, line=node.lineno,
                where=node.name,
                message=(f"scheduler decision site {node.name} does "
                         "not journal: every control action must "
                         "emit a kind=sched record "
                         "(self.journal(...)) — no silent "
                         "evictions/demotions/re-plans")))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel in FILES:
        path = root / rel
        if not path.exists():
            continue
        findings += check_source(path.read_text(), rel)
    return findings
