"""Perf-plane discipline: device syncs in hot loops must be sampled
(PF001).

``runtime/perf.py`` measures device time by fencing
(``jax.block_until_ready``) every ``perf.sample-every``-th step — the
other steps stay sync-free, which is the whole point: an UNSAMPLED
fence (or a per-step ``memory_stats()`` / ``live_arrays()`` poll) in a
hot loop stalls the async dispatch pipeline every tick and silently
halves throughput, exactly the class of regression the jaxpr auditor's
JX001 exists for.  JX001 flags syncs applied to jitted results; this
analyzer closes the remaining gap: it holds every
``block_until_ready`` / ``memory_stats`` / ``live_buffers`` /
``live_arrays`` call inside a hot region to the sampler discipline —
the call must sit under an ``if`` whose condition names the sampler
(``...sampled...``), or carry an explicit ``# slcheck: sampled-gate``
annotation for audited exceptions.

Scanned regions: the jaxpr auditor's hot-function registry
(``client.py`` tick loops, ``context.py _drive_columns``) plus the
perf plane's own step path (``perf.py SampledStepTimer.note_step`` — scanned
in ``all`` mode precisely so the repo's one legitimate hot-loop fence
is PROVEN to sit behind the gate, not just assumed to).
"""

from __future__ import annotations

import ast
import pathlib
import re

from split_learning_tpu.analysis.findings import Finding
from split_learning_tpu.analysis.jaxpr_audit import HOT_FUNCTIONS

#: device-sync / device-introspection calls the sampler must gate
SYNC_NAMES = frozenset({"block_until_ready", "memory_stats",
                        "live_buffers", "live_arrays"})

#: perf.py's own step path: "all" mode (the whole body is hot — it
#: runs once per training step)
PERF_HOT = {
    "split_learning_tpu/runtime/perf.py": {"note_step": "all"},
}

_ANNOT_RE = re.compile(r"#\s*slcheck:\s*(.+?)\s*$")


def _annotated(lines: list[str], lineno: int, tag: str) -> bool:
    if 0 < lineno <= len(lines):
        m = _ANNOT_RE.search(lines[lineno - 1])
        return bool(m and tag in m.group(1))
    return False


class _Visitor(ast.NodeVisitor):
    """Flag ungated sync calls inside the hot region of one function."""

    def __init__(self, rel: str, fn_name: str, mode: str,
                 lines: list[str]):
        self.rel = rel
        self.fn_name = fn_name
        self.mode = mode
        self.lines = lines
        self.loop_depth = 0
        self.gate_depth = 0
        self.findings: list[Finding] = []

    def _visit_loop(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While = _visit_loop

    def visit_If(self, node: ast.If):
        # only the branch that runs WHEN the sampler fired is gated:
        # `if ...sampled...:` gates its body, `if not ...sampled...:`
        # gates its else — the other branch runs every unsampled step
        # and must stay sync-free.  A sync in the test itself is never
        # gated (it evaluates on every step).
        inverted = (isinstance(node.test, ast.UnaryOp)
                    and isinstance(node.test.op, ast.Not)
                    and "sampled" in ast.unparse(node.test.operand))
        body_gated = (not inverted
                      and "sampled" in ast.unparse(node.test))
        self.visit(node.test)
        for branch, gated in ((node.body, body_gated),
                              (node.orelse, inverted)):
            if gated:
                self.gate_depth += 1
            for child in branch:
                self.visit(child)
            if gated:
                self.gate_depth -= 1

    def _hot(self) -> bool:
        return self.mode == "all" or self.loop_depth > 0

    def visit_Call(self, node: ast.Call):
        f = node.func
        name = (f.attr if isinstance(f, ast.Attribute)
                else f.id if isinstance(f, ast.Name) else None)
        if (name in SYNC_NAMES and self._hot()
                and self.gate_depth == 0
                and not _annotated(self.lines, node.lineno,
                                   "sampled-gate")):
            self.findings.append(Finding(
                "PF001", self.rel, node.lineno, self.fn_name,
                f"unsampled {name}() in a hot loop: device syncs must "
                "sit behind the perf sampler gate (an `if ...sampled` "
                "guard, runtime/perf.py SampledStepTimer) or carry "
                "`# slcheck: sampled-gate`"))
        self.generic_visit(node)


def scan_source(source: str, rel: str,
                funcs: dict[str, str]) -> list[Finding]:
    """PF001 findings for the named functions of one source file."""
    findings: list[Finding] = []
    lines = source.splitlines()
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in funcs:
            v = _Visitor(rel, node.name, funcs[node.name], lines)
            v.visit(node)
            findings += v.findings
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    regions: dict[str, dict[str, str]] = {}
    for rel, funcs in list(HOT_FUNCTIONS.items()) + list(PERF_HOT.items()):
        regions.setdefault(rel, {}).update(funcs)
    for rel, funcs in sorted(regions.items()):
        path = root / rel
        try:
            source = path.read_text()
        except OSError:
            continue
        findings += scan_source(source, rel, funcs)
    return findings
