"""Protocol conformance checker (static + codec self-test).

Three passes over the protocol surface, all driven by the declarative
model in :mod:`split_learning_tpu.analysis.model`:

* **send/recv site conformance** (PC001-PC003, PC008): AST-walk
  ``runtime/client.py`` / ``runtime/server.py`` and verify every
  ``bus.publish`` / ``bus.get`` names a frame type, queue family and
  direction :data:`~split_learning_tpu.analysis.model.SEND_RULES`
  allows.  ``runtime/bus.py`` / ``runtime/chaos.py`` are checked for
  the transport invariant instead: a transport layer forwards its
  caller's queue (or its own ``__ack__`` side channel) and never
  originates application-queue traffic.
* **codec coverage** (PC004-PC006): every member of
  ``CONTROL_TYPES``/``DATA_TYPES`` must round-trip through
  ``encode``/``decode`` (TENSOR framing for ``TENSOR_TYPES``), reject
  a corrupted frame *before* interpreting payload bytes (checked both
  at runtime with a bit flip and in the AST: any function calling
  ``np.frombuffer`` or ``.load()`` must run a ``zlib.crc32`` check
  first), and ride a queue family the default chaos-injection patterns
  cover.
* **handler coverage** (PC007): the message kinds each role
  ``isinstance``-dispatches on must match what the model says the role
  can receive.

Inline annotations (``# slcheck: ...`` trailing comments) feed the
checker facts the AST cannot recover:

* ``# slcheck: wire=EpochEnd`` — this publish forwards an undecoded
  raw frame of the named kind (the middle-stage fence relay);
* ``# slcheck: allow-send`` — suppress PC001/PC002 on this line.
"""

from __future__ import annotations

import ast
import pathlib
import re

from split_learning_tpu.analysis.findings import Finding
from split_learning_tpu.analysis.model import (
    ALL_KINDS, DATA_KINDS, RECV_RULES, SEND_RULES, queue_family,
)

_QUEUE_CTORS = {"reply_queue": "reply", "intermediate_queue":
                "intermediate", "gradient_queue": "gradient",
                "aggregate_queue": "aggregate", "_ack_queue": "ack",
                "digest_queue": "digest"}
_ANNOT_RE = re.compile(r"#\s*slcheck:\s*(.+?)\s*$")


def _annotations(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _ANNOT_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def _call_name(node: ast.AST) -> str | None:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute):
            return f.attr
    return None


class _QueueEnv:
    """Per-function resolution of queue expressions to families."""

    def __init__(self, cls_methods: dict[str, ast.FunctionDef]):
        self.cls_methods = cls_methods
        self.names: dict[str, str] = {}

    def family_of(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name in _QUEUE_CTORS:
                return _QUEUE_CTORS[name]
            if (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and name in self.cls_methods):
                return self._family_of_method(name)
            return None
        if isinstance(node, ast.Name):
            if node.id == "RPC_QUEUE":
                return "rpc"
            return self.names.get(node.id)
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return queue_family(node.value)
        if isinstance(node, ast.Subscript):
            return self.family_of(node.value)
        if isinstance(node, (ast.List, ast.Tuple)):
            fams = {self.family_of(e) for e in node.elts}
            return fams.pop() if len(fams) == 1 else None
        return None

    def _family_of_method(self, name: str) -> str | None:
        """Family of a same-class helper that builds queue names
        (e.g. ``_out_queues``): unique ctor family in its returns."""
        fams = set()
        for node in ast.walk(self.cls_methods[name]):
            cn = _call_name(node)
            if cn in _QUEUE_CTORS:
                fams.add(_QUEUE_CTORS[cn])
        return fams.pop() if len(fams) == 1 else None

    def note(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            fam = self.family_of(stmt.value)
            if fam is not None:
                self.names[stmt.targets[0].id] = fam
        elif isinstance(stmt, (ast.For, ast.AsyncFor)) \
                and isinstance(stmt.target, ast.Name):
            fam = self.family_of(stmt.iter)
            if fam is not None:
                self.names[stmt.target.id] = fam


def _message_kind(node: ast.AST, fn: ast.FunctionDef | None,
                  env_assigns: dict[str, str]) -> str | None:
    """Resolve the frame kind a publish payload expression carries."""
    if isinstance(node, ast.Lambda):
        return _message_kind(node.body, fn, env_assigns)
    name = _call_name(node)
    if name in ("encode", "encode_parts", "encode_pickled"):
        inner = node.args[0] if getattr(node, "args", None) else None
        if inner is None:
            return None
        inner_name = _call_name(inner)
        if inner_name in ALL_KINDS:
            return inner_name
        if isinstance(inner, ast.Name):
            if inner.id in env_assigns:
                return env_assigns[inner.id]
            if fn is not None:    # typed parameter, e.g. ``msg: Stop``
                for a in fn.args.args:
                    if (a.arg == inner.id
                            and isinstance(a.annotation, ast.Name)
                            and a.annotation.id in ALL_KINDS):
                        return a.annotation.id
        return None
    if name in ALL_KINDS:
        return name
    return None


def _iter_functions(tree: ast.Module):
    """(classdef-or-None, functiondef) pairs, outermost functions."""
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            yield None, node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    yield node, sub


def _check_role_file(path: pathlib.Path, rel: str,
                     role: str) -> list[Finding]:
    source = path.read_text()
    tree = ast.parse(source)
    notes = _annotations(source)
    findings: list[Finding] = []

    for cls, fn in _iter_functions(tree):
        methods = ({m.name: m for m in cls.body
                    if isinstance(m, ast.FunctionDef)} if cls else {})
        env = _QueueEnv(methods)
        kinds_env: dict[str, str] = {}
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.stmt):
                env.note(stmt)
            if isinstance(stmt, ast.Assign) \
                    and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                k = _call_name(stmt.value)
                if k in ALL_KINDS:
                    kinds_env[stmt.targets[0].id] = k
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            target = ast.unparse(f.value)
            note = notes.get(node.lineno, "")
            if f.attr in ("publish", "_publish_parts") and target in (
                    "self.bus", "self._publish_parts", "self") \
                    and len(node.args) >= 2:
                if "allow-send" in note:
                    continue
                # a queue that is this function's own PARAMETER marks a
                # publish wrapper (client._publish_parts): its call
                # sites are the real send sites
                if isinstance(node.args[0], ast.Name) and \
                        node.args[0].id in {a.arg for a in fn.args.args}:
                    continue
                fam = env.family_of(node.args[0])
                kind = _message_kind(node.args[1], fn, kinds_env)
                m = re.search(r"wire=(\w+)", note)
                if kind is None and m:
                    kind = m.group(1)
                if fam is None or kind is None:
                    findings.append(Finding(
                        "PC002", rel, node.lineno, fn.name,
                        f"unresolved publish site (family={fam}, "
                        f"kind={kind}); name the frame with "
                        "'# slcheck: wire=<Kind>' if the AST cannot"))
                elif (role, fam, kind) not in SEND_RULES:
                    findings.append(Finding(
                        "PC001", rel, node.lineno, fn.name,
                        f"model forbids {role} sending {kind} on "
                        f"{fam} queue"))
            elif f.attr == "get" and target == "self.bus" \
                    and node.args:
                fam = env.family_of(node.args[0])
                if fam is None:
                    findings.append(Finding(
                        "PC002", rel, node.lineno, fn.name,
                        "unresolved bus.get queue family"))
                elif (role, fam) not in RECV_RULES:
                    findings.append(Finding(
                        "PC003", rel, node.lineno, fn.name,
                        f"model forbids {role} consuming from {fam} "
                        "queue"))
    return findings


_PASSTHROUGH_ARGS = {"queue", "q", "ackq"}


def _check_transport_file(path: pathlib.Path, rel: str) -> list[Finding]:
    """Transport layers (bus/chaos) must never originate traffic on an
    application queue: every publish/get forwards the caller's queue
    variable or targets the ``__ack__`` side channel."""
    tree = ast.parse(path.read_text())
    findings: list[Finding] = []
    for cls, fn in _iter_functions(tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) \
                    or f.attr not in ("publish", "get"):
                continue
            target = ast.unparse(f.value)
            if not any(t in target for t in
                       ("self.inner", "self._side", "self.src",
                        "self._store")):
                continue
            if not node.args:
                continue
            q = node.args[0]
            ok = (isinstance(q, ast.Name)
                  and q.id in _PASSTHROUGH_ARGS) \
                or (isinstance(q, ast.Attribute) and q.attr == "queue") \
                or _call_name(q) == "_ack_queue"
            if not ok:
                findings.append(Finding(
                    "PC008", rel, node.lineno, fn.name,
                    f"transport layer {f.attr} on non-passthrough "
                    f"queue expression {ast.unparse(q)!r}"))
    return findings


# -- codec coverage ---------------------------------------------------------

def _sample_messages():
    import numpy as np

    from split_learning_tpu.runtime import protocol as P
    return {
        "Register": P.Register(client_id="c", stage=1),
        "Ready": P.Ready(client_id="c"),
        "Notify": P.Notify(client_id="c", cluster=0),
        "Update": P.Update(
            client_id="c", stage=1, cluster=0,
            params={"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
            num_samples=3),
        "Start": P.Start(start_layer=0, end_layer=-1, cluster=0,
                         params={"w": np.ones((2,), np.float32)}),
        "Syn": P.Syn(),
        "Pause": P.Pause(),
        "Stop": P.Stop(),
        "Heartbeat": P.Heartbeat(
            client_id="c", round_idx=1,
            telemetry={"part": "c", "t": 1.0, "seq": 1,
                       "counters": {"drops": 2}}),
        "PartialAggregate": P.PartialAggregate(
            aggregator_id="aggregator_0_0", cluster=0, group=0,
            stage=1, round_idx=1,
            sums={"w": np.arange(4, dtype=np.float32)}, weight=3.0,
            dtypes={"w": "float32"},
            stat_sums={"m": np.ones((2,), np.float32)},
            stat_weight=3.0, stat_dtypes={"m": "float32"},
            n_samples=12, members=[{"client_id": "c", "stage": 1,
                                    "num_samples": 12, "ok": True}],
            level=2, codec="int8:64", codec_base=3),
        "AggHello": P.AggHello(node_id="aggregator_node_0",
                               capacity=4),
        "AggAssign": P.AggAssign(
            node_id="aggregator_node_0", cluster=0, gen=3,
            round_idx=1,
            groups=[{"idx": 0, "stage": 1, "level": 1,
                     "members": ["c"], "parent": 2}],
            deadline_s=30.0, codec="delta:int8:64",
            bases={1: {"w": np.ones((4,), np.float32)}},
            chunk_bytes=1 << 20),
        "AggFlush": P.AggFlush(node_id="aggregator_node_0", gen=3),
        "FleetDigest": P.FleetDigest(
            node_id="aggregator_node_0", round_idx=1,
            digest={"v": 1, "node": "aggregator_node_0", "t": 1.0,
                    "seq": 2, "clients": 3,
                    "states": {"healthy": 2, "straggler": 1},
                    "counters": {"drops": 4}, "samples": 96,
                    "rate": {"v": 1, "n": 3, "zero": 0,
                             "total": 30.0, "b": {"13": 3}},
                    "crate": {"v": 1, "n": 3, "zero": 0,
                              "total": 33.0, "b": {"13": 3}},
                    "stages": {}, "worst": [
                        {"client": "c", "state": "straggler",
                         "score": 0.3, "view": {"stage": 1}}],
                    "transitions": []}),
        "DigestRoute": P.DigestRoute(client_id="c", queue=None),
        "BlackboxDump": P.BlackboxDump(
            participant="c", reason="lost:client_2_1", t_req=1.0),
        "StageHello": P.StageHello(host_id="stage_host_0", capacity=2),
        "StageAssign": P.StageAssign(
            host_id="stage_host_0", gen=3, round_idx=1,
            slots=[{"client_id": "pipeline_s2_0", "stage": 2,
                    "cluster": 0}]),
        "Activation": P.Activation(
            data_id="d0", data=np.ones((2, 3), np.float32),
            labels=np.zeros((2,), np.int64), trace=["c"], cluster=0),
        "Gradient": P.Gradient(
            data_id="d0", data=np.ones((2, 3), np.float32), trace=[]),
        "EpochEnd": P.EpochEnd(client_id="c"),
    }


def _trees_equal(a, b) -> bool:
    import numpy as np
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _trees_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            _trees_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (np.asarray(a).dtype == np.asarray(b).dtype
                and np.array_equal(np.asarray(a), np.asarray(b)))
    return a == b


def _check_codec() -> list[Finding]:
    import dataclasses as dc

    from split_learning_tpu.config import ChaosConfig
    from split_learning_tpu.runtime import protocol as P

    rel = "split_learning_tpu/runtime/protocol.py"
    findings: list[Finding] = []
    declared = {t.__name__ for t in P.CONTROL_TYPES + P.DATA_TYPES}
    samples = _sample_messages()
    for kind in sorted(declared | set(samples)):
        if kind not in P._TYPE_BY_NAME:
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"{kind} has no encoder dispatch entry (_TYPE_BY_NAME)"))
            continue
        msg = samples.get(kind)
        if msg is None:
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"{kind} is declared but the codec self-test has no "
                "sample for it — add one to _sample_messages"))
            continue
        try:
            frame = P.encode(msg)
            back = P.decode(frame)
        except Exception as e:  # noqa: BLE001 — any failure is the finding
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"{kind} does not round-trip: {type(e).__name__}: {e}"))
            continue
        if type(back) is not type(msg) or not _trees_equal(
                dc.asdict(msg), dc.asdict(back)):
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"{kind} round-trip changed the message"))
            continue
        if isinstance(msg, P.TENSOR_TYPES) \
                and frame[:4] != P.TENSOR_MAGIC:
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"{kind} is a TENSOR type but did not use SLT2 framing"))
        # corruption must be rejected before payload interpretation
        i = len(frame) // 2
        corrupt = frame[:i] + bytes([frame[i] ^ 0xFF]) + frame[i + 1:]
        try:
            P.decode(corrupt)
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"corrupted {kind} frame decoded without an integrity "
                "error"))
        except P.CorruptFrame:
            pass
        except Exception as e:  # noqa: BLE001 — reached the unpickler
            findings.append(Finding(
                "PC004", rel, 0, kind,
                f"corrupted {kind} frame escaped the checksum and "
                f"raised {type(e).__name__} from payload decoding"))
        # chunk framing must reassemble
        if isinstance(msg, P.TENSOR_TYPES):
            try:
                parts = P.encode_parts(msg, max_bytes=64)
                asm = P.FrameAssembler()
                out = None
                for part in parts:
                    out = asm.feed(part)
                assert out is not None and type(out) is type(msg)
            except Exception as e:  # noqa: BLE001 — the finding
                findings.append(Finding(
                    "PC004", rel, 0, kind,
                    f"{kind} chunked round-trip failed: "
                    f"{type(e).__name__}: {e}"))
        # chaos-injection coverage: the queue families this kind rides
        # must be matched by the default fault-injection patterns
        if kind in DATA_KINDS or kind in (
                t.__name__ for t in P.TENSOR_TYPES):
            fams = {fam for role, fam, k in SEND_RULES if k == kind}
            examples = {"rpc": "rpc_queue", "reply": "reply_c",
                        "intermediate": "intermediate_queue_1_0",
                        "gradient": "gradient_queue_1_c",
                        "aggregate": "aggregate_queue_0_0"}
            import fnmatch
            pats = ChaosConfig().queues
            for fam in fams:
                if not any(fnmatch.fnmatchcase(examples[fam], p)
                           for p in pats):
                    findings.append(Finding(
                        "PC006", rel, 0, kind,
                        f"{kind} rides {fam} queues but no default "
                        f"chaos pattern {pats} matches them — faults "
                        "on this path are untestable"))
    return findings


_RISKY_CALLS = ("frombuffer", "load")


def _check_crc_order(path: pathlib.Path, rel: str) -> list[Finding]:
    """Any protocol function interpreting payload bytes
    (``np.frombuffer`` / unpickler ``.load``) must run a
    ``zlib.crc32`` integrity check at an earlier line."""
    tree = ast.parse(path.read_text())
    findings: list[Finding] = []
    for _, fn in _iter_functions(tree):
        risky: list[tuple[int, str]] = []
        crc_lines: list[int] = []
        for node in ast.walk(fn):
            name = _call_name(node)
            if name in _RISKY_CALLS:
                risky.append((node.lineno, name))
            if name == "crc32":
                crc_lines.append(node.lineno)
        if not risky:
            continue
        first_risky = min(line for line, _ in risky)
        if not crc_lines or min(crc_lines) > first_risky:
            what = ", ".join(sorted({n for _, n in risky}))
            findings.append(Finding(
                "PC005", rel, first_risky, fn.name,
                f"{what} runs before any crc32 integrity check in "
                f"{fn.name}"))
    return findings


def _check_handlers(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    receivable = {
        role: {k for r, fam, k in SEND_RULES
               if (role, fam) in RECV_RULES}
        for role in ("client", "server")
    }
    must_handle = {"client": {"Start", "Syn", "Pause", "Stop"},
                   "server": {"Register", "Ready", "Notify", "Update",
                              "Heartbeat", "PartialAggregate",
                              "AggHello", "StageHello"}}
    for role in ("client", "server"):
        rel = f"split_learning_tpu/runtime/{role}.py"
        tree = ast.parse((root / rel).read_text())
        handled: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "isinstance" \
                    and len(node.args) == 2:
                arg = node.args[1]
                names = ([arg] if isinstance(arg, ast.Name)
                         else list(arg.elts)
                         if isinstance(arg, ast.Tuple) else [])
                for n in names:
                    if isinstance(n, ast.Name) and n.id in ALL_KINDS:
                        handled.add(n.id)
        for kind in sorted(handled - receivable[role]):
            findings.append(Finding(
                "PC007", rel, 0, kind,
                f"{role} dispatches on {kind}, which the model says "
                f"it can never receive"))
        for kind in sorted(must_handle[role] - handled):
            findings.append(Finding(
                "PC007", rel, 0, kind,
                f"{role} never dispatches on {kind}, which the model "
                f"says it must handle"))
    return findings


def run(root: pathlib.Path) -> list[Finding]:
    findings: list[Finding] = []
    for rel, role in (("split_learning_tpu/runtime/client.py", "client"),
                      ("split_learning_tpu/runtime/server.py", "server")):
        findings += _check_role_file(root / rel, rel, role)
    for rel in ("split_learning_tpu/runtime/bus.py",
                "split_learning_tpu/runtime/chaos.py"):
        findings += _check_transport_file(root / rel, rel)
    findings += _check_crc_order(
        root / "split_learning_tpu/runtime/protocol.py",
        "split_learning_tpu/runtime/protocol.py")
    findings += _check_codec()
    findings += _check_handlers(root)
    return findings
