"""jax version bridge: modern ``jax.shard_map`` on older jax installs.

The data plane is written against the current API — ``jax.shard_map``
taking ``check_vma=`` and (for partially-auto meshes) ``axis_names=``.
Some baked-in toolchains still ship jax 0.4.x, where the same machinery
lives at ``jax.experimental.shard_map.shard_map`` with the older
``check_rep=`` / ``auto=`` spelling:

* ``check_vma``  -> ``check_rep`` (both disable the replication/varying
  tracker whose false positives the pipeline avoids);
* ``axis_names`` (the MANUAL axes) -> ``auto`` (its complement over the
  mesh axes).

:func:`install` publishes the bridge as ``jax.shard_map`` exactly when
the attribute is missing, so on a modern jax this module is a no-op and
the native implementation is always preferred.  Importing
``split_learning_tpu`` installs it once per process.

Known bridge limitation: partially-auto meshes (a ``model``/``expert``
GSPMD axis next to manual ``client``/``stage``) hit jax 0.4.x's
immature ``auto=`` support — XLA rejects the lowered ``PartitionId``
("UNIMPLEMENTED ... SPMD partitioning").  The fully-manual paths (the
whole (client, stage[, seq]) pipeline data plane, FedAvg, ZeRO-1,
sliced params) bridge cleanly; TP/EP composition needs a modern jax.
"""

from __future__ import annotations

import functools

import jax


def _legacy_shard_map():
    from jax.experimental.shard_map import shard_map as _sm

    @functools.wraps(_sm)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
                  axis_names=None):
        kwargs = {"check_rep": bool(check_vma)}
        if axis_names is not None:
            kwargs["auto"] = frozenset(
                set(mesh.axis_names) - set(axis_names))
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   **kwargs)

    return shard_map


def install() -> None:
    """Install the modern API names the running jax may predate."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _legacy_shard_map()
    if not hasattr(jax.lax, "axis_size"):
        # the classic spelling of a manual axis' size
        jax.lax.axis_size = lambda axis_name: jax.lax.psum(1, axis_name)


install()
