"""Split-aware model zoo.

Every model is expressed once as an ordered list of indexed
:class:`~split_learning_tpu.models.split.LayerSpec` entries; the generic
:class:`~split_learning_tpu.models.split.SplitModel` materializes any
contiguous slice of it — the TPU-native counterpart of the reference's
per-model ``Klass(start_layer, end_layer)`` pattern
(``/root/reference/src/model/VGG16_CIFAR10.py:4-9``) without one class per
model/shard combination.
"""

from split_learning_tpu.models.split import (
    LayerSpec, SplitModel, build_model, model_registry, register_model,
    shard_params, merge_shard_params, num_layers,
)
import split_learning_tpu.models.vgg  # noqa: F401  (registers VGG16_*)
import split_learning_tpu.models.bert  # noqa: F401  (registers BERT_*)
import split_learning_tpu.models.kwt  # noqa: F401  (registers KWT_*)
import split_learning_tpu.models.vit  # noqa: F401  (registers ViT_*)
import split_learning_tpu.models.mobilenet  # noqa: F401  (MobileNetv1_*)
import split_learning_tpu.models.resnet  # noqa: F401  (ResNet50_*)
import split_learning_tpu.models.llama  # noqa: F401  (TinyLlama_*)

__all__ = [
    "LayerSpec", "SplitModel", "build_model", "model_registry",
    "register_model", "shard_params", "merge_shard_params", "num_layers",
]
