"""Generic layer-indexed model splitting.

The reference materializes a model shard with per-layer ``if start < i <=
end`` guards duplicated across every model file
(``/root/reference/src/model/VGG16_CIFAR10.py:9-117``).  Here the same
semantics — 1-based layer indices, a shard owns layers ``start+1..end``,
``end == -1`` means "to the end" — live once in :class:`SplitModel`, and a
model is just a tuple of :class:`LayerSpec`.

Shard parameters are keyed by **absolute** layer name (``layer7`` is
``layer7`` in every shard and in the full model), so shard state transfer,
FedAvg across shards, and full-model reassembly are plain dict slicing —
the pytree analog of the reference's state_dict key matching
(``src/Server.py:230-256``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    """One indexed layer of a splittable model.

    ``make`` builds a flax module given a ``name`` (parametric layers) or
    returns ``None`` with ``fn`` set instead (param-free ops: activation,
    pooling, reshape).  ``fn`` signature: ``fn(module_or_none, x, train)``.
    """
    name: str
    make: Callable[..., nn.Module] | None = None
    fn: Callable[..., Any] | None = None

    def __post_init__(self):
        if self.make is None and self.fn is None:
            raise ValueError(
                f"LayerSpec {self.name}: at least one of make/fn required")


class SplitModel(nn.Module):
    """A contiguous slice ``start_layer+1 .. end_layer`` of a layer list.

    ``start_layer=0, end_layer=-1`` (or ``len(specs)``) is the full model.
    Layer indices are 1-based to match the reference's protocol surface
    (cut layers, ``layers`` ranges in START messages).
    """
    specs: tuple  # tuple[LayerSpec, ...] — static, hashable for jit
    start_layer: int = 0
    end_layer: int = -1

    @property
    def resolved_end(self) -> int:
        return len(self.specs) if self.end_layer == -1 else self.end_layer

    def setup(self):
        owned = {}
        for i, spec in enumerate(self.specs, start=1):
            if self.start_layer < i <= self.resolved_end and spec.make:
                owned[spec.name] = spec.make(name=spec.name)
        self._owned = owned

    def __call__(self, x, train: bool = False):
        for i, spec in enumerate(self.specs, start=1):
            if not (self.start_layer < i <= self.resolved_end):
                continue
            if spec.make:
                mod = self._owned[spec.name]
                x = spec.fn(mod, x, train) if spec.fn else mod(x)
            else:
                x = spec.fn(None, x, train)
        return x


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

_REGISTRY: dict[str, Callable[..., tuple]] = {}


def register_model(name: str):
    """Decorator: register a ``(**kw) -> tuple[LayerSpec, ...]`` spec builder."""
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def model_registry() -> dict[str, Callable[..., tuple]]:
    return dict(_REGISTRY)


def build_model(name: str, start_layer: int = 0, end_layer: int = -1,
                **kwargs) -> SplitModel:
    """Instantiate a shard of a registered model.

    ``name`` follows the reference's ``{MODEL}_{DATASET}`` convention
    (e.g. ``VGG16_CIFAR10``).
    """
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    specs = _REGISTRY[name](**kwargs)
    return SplitModel(specs=specs, start_layer=start_layer,
                      end_layer=end_layer)


def num_layers(name: str, **kwargs) -> int:
    return len(_REGISTRY[name](**kwargs))


# --------------------------------------------------------------------------
# shard pytree slicing
# --------------------------------------------------------------------------

def _layer_index(specs: Sequence[LayerSpec], layer_name: str) -> int:
    for i, s in enumerate(specs, start=1):
        if s.name == layer_name:
            return i
    raise KeyError(layer_name)


def shard_params(full_tree: dict, specs: Sequence[LayerSpec],
                 start_layer: int, end_layer: int) -> dict:
    """Slice a full-model variable collection down to one shard's layers.

    Works on any collection dict keyed by layer name at the top level
    (``params``, ``batch_stats``).  ``end_layer == -1`` means to-the-end.
    """
    end = len(specs) if end_layer == -1 else end_layer
    return {
        k: v for k, v in full_tree.items()
        if start_layer < _layer_index(specs, k) <= end
    }


def merge_shard_params(full_tree: dict, *shard_trees: dict) -> dict:
    """Overlay shard collections onto a full-model collection (reassembly)."""
    out = dict(full_tree)
    for sd in shard_trees:
        out.update(sd)
    return out


# --------------------------------------------------------------------------
# param-free op helpers for LayerSpec.fn
# --------------------------------------------------------------------------

def relu_fn(_, x, train):
    return nn.relu(x)


def gelu_fn(_, x, train):
    return nn.gelu(x)


def maxpool2_fn(_, x, train):
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


def flatten_fn(_, x, train):
    return x.reshape((x.shape[0], -1))


def dropout_layer(rate: float):
    """Dropout as a parametric-less module layer (needs an rng when train)."""
    def make(name=None):
        return nn.Dropout(rate=rate, name=name)

    def fn(mod, x, train):
        return mod(x, deterministic=not train)
    return make, fn


def conv_fn(mod, x, train):
    return mod(x)


def module_train_fn(mod, x, train):
    """Module whose __call__ takes a ``train`` kwarg (dropout inside)."""
    return mod(x, train=train)


def module_plain_fn(mod, x, train):
    """Module whose __call__ ignores train mode."""
    return mod(x)


def batchnorm_fn(mod, x, train):
    return mod(x, use_running_average=not train)


def identity_fn(_, x, train):
    return x


def astype_fn(dtype):
    def fn(_, x, train):
        return jnp.asarray(x, dtype)
    return fn
