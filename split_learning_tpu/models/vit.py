"""ViT as 12 indexed layers (Vanilla_SL variant parity).

Layer indexing matches ``/root/reference/other/Vanilla_SL/src/model/
ViT_CIFAR10.py:29-116``: 1 = patch conv (4x4 stride 4, embed 128),
2 = patch flatten, 3 = CLS-token concat (a learned parameter layer),
4 = learned position embedding, 5-10 = six pre-LN encoder blocks
(4 heads, MLP 256), 11 = LayerNorm over the CLS token, 12 = linear head.
NHWC + fused-qkv attention instead of the reference's NCHW + per-tensor
``nn.MultiheadAttention``.

``ViT_S16_CIFAR10`` is the north-star scale-up (BASELINE.json config #4):
ViT-S geometry (384 embed, 6 heads, 12 blocks, MLP 1536) over the same
split-layer contract, 18 layers total.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model, module_plain_fn as _plain_fn,
    module_train_fn as _train_fn,
)
from split_learning_tpu.models.transformer import PreLNBlock


class PatchFlatten(nn.Module):
    """(B, H', W', C) -> (B, H'*W', C) token sequence."""

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        return x.reshape(b, h * w, c)


class ClsToken(nn.Module):
    """Prepend a learned CLS token (reference ``layer3``/cls_token)."""
    embed_dim: int

    @nn.compact
    def __call__(self, x):
        cls = self.param("cls", nn.initializers.normal(1.0),
                         (1, 1, self.embed_dim))
        cls = jnp.broadcast_to(cls.astype(x.dtype),
                               (x.shape[0], 1, self.embed_dim))
        return jnp.concatenate([cls, x], axis=1)


class PosEmbed(nn.Module):
    """Learned position embedding over tokens (reference ``pos_embed``)."""
    n_tokens: int
    embed_dim: int

    @nn.compact
    def __call__(self, x):
        pos = self.param("pos", nn.initializers.normal(1.0),
                         (1, self.n_tokens, self.embed_dim))
        return x + pos.astype(x.dtype)


class ClsNorm(nn.Module):
    """LayerNorm applied to the CLS token only (reference ``layer11``)."""

    @nn.compact
    def __call__(self, x):
        return nn.LayerNorm(name="norm")(x[:, 0])


def _vit_specs(img_size: int, num_classes: int,
               patch_size: int = 4, embed_dim: int = 128,
               num_heads: int = 4, mlp_dim: int = 256, n_block: int = 6,
               dropout_rate: float = 0.0, dtype=jnp.float32) -> tuple:
    n_tokens = (img_size // patch_size) ** 2 + 1
    specs = [
        LayerSpec("layer1", make=functools.partial(
            nn.Conv, features=embed_dim,
            kernel_size=(patch_size, patch_size),
            strides=(patch_size, patch_size), padding="VALID", dtype=dtype),
            fn=_plain_fn),
        LayerSpec("layer2", make=PatchFlatten, fn=_plain_fn),
        LayerSpec("layer3", make=functools.partial(
            ClsToken, embed_dim=embed_dim), fn=_plain_fn),
        LayerSpec("layer4", make=functools.partial(
            PosEmbed, n_tokens=n_tokens, embed_dim=embed_dim),
            fn=_plain_fn),
    ]
    for i in range(n_block):
        specs.append(LayerSpec(
            f"layer{5 + i}",
            make=functools.partial(
                PreLNBlock, embed_dim=embed_dim, num_heads=num_heads,
                mlp_dim=mlp_dim, dropout_rate=dropout_rate, dtype=dtype),
            fn=_train_fn))
    specs.append(LayerSpec(f"layer{5 + n_block}", make=ClsNorm,
                           fn=_plain_fn))
    specs.append(LayerSpec(
        f"layer{6 + n_block}",
        make=functools.partial(nn.Dense, features=num_classes, dtype=dtype),
        fn=_plain_fn))
    return tuple(specs)


@register_model("ViT_CIFAR10")
def vit_cifar10(dtype=jnp.float32, **kw) -> tuple:
    """CIFAR-10 ViT: (B, 32, 32, 3) NHWC -> 10 classes, 12 layers."""
    specs = _vit_specs(32, 10, dtype=dtype, **kw)
    if not kw:
        assert len(specs) == 12
    return specs


@register_model("ViT_MNIST")
def vit_mnist(dtype=jnp.float32, **kw) -> tuple:
    """MNIST ViT: (B, 28, 28, 1) -> 10 classes, 12 layers."""
    return _vit_specs(28, 10, dtype=dtype, **kw)


@register_model("ViT_S16_CIFAR10")
def vit_s16_cifar10(dtype=jnp.float32, **kw) -> tuple:
    """ViT-S geometry on CIFAR-10 (north-star config #4): patch 4 (CIFAR
    scale for 8x8 tokens), 384 wide, 6 heads, 12 blocks -> 18 layers."""
    defaults = dict(patch_size=4, embed_dim=384, num_heads=6,
                    mlp_dim=1536, n_block=12, dropout_rate=0.1)
    defaults.update(kw)
    return _vit_specs(32, 10, dtype=dtype, **defaults)
