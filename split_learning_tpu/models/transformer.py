"""Shared transformer building blocks (BERT post-LN, ViT/KWT pre-LN).

Fresh flax implementations of the block shapes the reference hand-rolls in
torch (``/root/reference/src/model/BERT_AGNEWS.py:39-141``,
``KWT_SPEECHCOMMANDS.py:5-23``).  Attention uses a single fused qkv einsum
path via ``nn.MultiHeadDotProductAttention`` — batched matmuls that XLA maps
straight onto the MXU — rather than the reference's per-projection matmul +
permute chain.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _attn_half(x, mask, train, *, hidden_size, num_heads, dropout_rate,
               dtype):
    """Attention half of a post-LN block: attn -> dropout -> add&norm.

    A plain function creating explicitly-named submodules in the
    CALLER's compact scope: :class:`BertBlock` and
    :class:`BertAttentionSublayer` share one body, so macro-block
    weights map 1:1 onto sublayer weights by construction."""
    attn = nn.MultiHeadDotProductAttention(
        num_heads=num_heads, qkv_features=hidden_size,
        out_features=hidden_size, dtype=dtype,
        dropout_rate=dropout_rate, name="attention")(
            x, x, mask=mask, deterministic=not train)
    attn = nn.Dropout(dropout_rate)(attn, deterministic=not train)
    return nn.LayerNorm(epsilon=1e-12, dtype=dtype,
                        name="attention_norm")(x + attn)


def _ffn_half(x, train, *, hidden_size, intermediate_size, dropout_rate,
              dtype):
    """FFN half of a post-LN block: dense-gelu-dense -> dropout ->
    add&norm (shared by :class:`BertBlock` / :class:`BertFfnSublayer`)."""
    h = nn.Dense(intermediate_size, dtype=dtype, name="intermediate")(x)
    h = nn.gelu(h)
    h = nn.Dense(hidden_size, dtype=dtype, name="output")(h)
    h = nn.Dropout(dropout_rate)(h, deterministic=not train)
    return nn.LayerNorm(epsilon=1e-12, dtype=dtype,
                        name="output_norm")(x + h)


class BertBlock(nn.Module):
    """Post-LN encoder block: attn -> add&norm -> FFN(gelu) -> add&norm."""
    hidden_size: int
    num_heads: int
    intermediate_size: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        x = _attn_half(x, mask, train, hidden_size=self.hidden_size,
                       num_heads=self.num_heads,
                       dropout_rate=self.dropout_rate, dtype=self.dtype)
        return _ffn_half(x, train, hidden_size=self.hidden_size,
                         intermediate_size=self.intermediate_size,
                         dropout_rate=self.dropout_rate, dtype=self.dtype)


class BertAttentionSublayer(nn.Module):
    """The attention half as a standalone split layer for fine-grained
    (per-sublayer) cut points (reference BERT_EMOTION's 27-layer
    indexing, ``other/Vanilla_SL/src/model/BERT_EMOTION.py:183-185``)."""
    hidden_size: int
    num_heads: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        return _attn_half(x, mask, train, hidden_size=self.hidden_size,
                          num_heads=self.num_heads,
                          dropout_rate=self.dropout_rate, dtype=self.dtype)


class BertFfnSublayer(nn.Module):
    """The FFN half as a standalone split layer (fine-grained cuts)."""
    hidden_size: int
    intermediate_size: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        del mask  # FFN is position-local; accepted for fn-signature parity
        return _ffn_half(x, train, hidden_size=self.hidden_size,
                         intermediate_size=self.intermediate_size,
                         dropout_rate=self.dropout_rate, dtype=self.dtype)


class PreLNBlock(nn.Module):
    """Pre-LN encoder block: x + attn(ln(x)); x + mlp(ln(x)) — the KWT/ViT
    shape."""
    embed_dim: int
    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask=None, train: bool = False):
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, qkv_features=self.embed_dim,
            out_features=self.embed_dim, dtype=self.dtype,
            name="attention")(h, h, mask=mask, deterministic=not train)
        x = x + attn
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.embed_dim, dtype=self.dtype, name="mlp_out")(h)
        if self.dropout_rate:
            h = nn.Dropout(self.dropout_rate)(h, deterministic=not train)
        return x + h


class BertEmbeddings(nn.Module):
    """Word + position + (zero) token-type embeddings, LN, dropout."""
    vocab_size: int
    hidden_size: int
    max_position_embeddings: int
    type_vocab_size: int = 2
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, input_ids, train: bool = False):
        seq = input_ids.shape[1]
        word = nn.Embed(self.vocab_size, self.hidden_size, dtype=self.dtype,
                        name="word_embeddings")(input_ids)
        pos_ids = jnp.arange(seq)[None, :]
        pos = nn.Embed(self.max_position_embeddings, self.hidden_size,
                       dtype=self.dtype, name="position_embeddings")(pos_ids)
        # token_type_ids default to zeros in the reference call path
        tok = nn.Embed(self.type_vocab_size, self.hidden_size,
                       dtype=self.dtype, name="token_type_embeddings")(
                           jnp.zeros_like(input_ids))
        x = word + pos + tok
        x = nn.LayerNorm(epsilon=1e-12, dtype=self.dtype, name="LayerNorm")(x)
        return nn.Dropout(self.dropout_rate)(x, deterministic=not train)


class Pooler(nn.Module):
    """CLS-token dense+tanh pooler."""
    hidden_size: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return nn.tanh(nn.Dense(self.hidden_size, dtype=self.dtype,
                                name="dense")(x[:, 0]))


class ClassifierHead(nn.Module):
    """Dropout + linear classification head."""
    num_labels: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dropout(self.dropout_rate)(x, deterministic=not train)
        return nn.Dense(self.num_labels, dtype=self.dtype,
                        name="classifier")(x)
