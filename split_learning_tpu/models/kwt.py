"""Keyword-Transformer over MFCC features as 17 indexed layers.

Indexing parity with the reference (``/root/reference/src/model/
KWT_SPEECHCOMMANDS.py:28-67``): 1 = linear patch embed (with the
time-major transpose), 2 = CLS token, 3 = positional embedding + dropout,
4-15 = pre-LN encoder blocks, 16 = LayerNorm on the CLS position,
17 = classification head.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model,
    module_train_fn as _train_fn, module_plain_fn as _plain_fn,
)
from split_learning_tpu.models.transformer import PreLNBlock


class _TimeMajorEmbed(nn.Module):
    """(B, n_mfcc, T) -> (B, T, embed_dim) linear embedding."""
    embed_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = jnp.swapaxes(x, 1, 2)
        return nn.Dense(self.embed_dim, dtype=self.dtype, name="embed")(x)


class _ClsToken(nn.Module):
    embed_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cls = self.param("cls_token",
                         nn.initializers.truncated_normal(0.02),
                         (1, 1, self.embed_dim))
        cls = jnp.broadcast_to(cls, (x.shape[0], 1, self.embed_dim))
        return jnp.concatenate([cls.astype(x.dtype), x], axis=1)


class _PosEmbed(nn.Module):
    seq_len: int
    embed_dim: int
    dropout_rate: float = 0.1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pos = self.param("pos_embed",
                         nn.initializers.truncated_normal(0.02),
                         (1, self.seq_len, self.embed_dim))
        x = x + pos.astype(x.dtype)
        return nn.Dropout(self.dropout_rate)(x, deterministic=not train)


def _cls_norm_fn(mod, x, train):
    return mod(x[:, 0])


@register_model("KWT_SPEECHCOMMANDS")
def kwt_speechcommands(n_mfcc: int = 40, time_steps: int = 98,
                       embed_dim: int = 64, num_heads: int = 1,
                       mlp_dim: int = 256, num_classes: int = 10,
                       dropout_rate: float = 0.1,
                       dtype=jnp.float32) -> tuple:
    specs = [
        LayerSpec("layer1",
                  make=functools.partial(_TimeMajorEmbed,
                                         embed_dim=embed_dim, dtype=dtype),
                  fn=_plain_fn),
        LayerSpec("layer2",
                  make=functools.partial(_ClsToken, embed_dim=embed_dim,
                                         dtype=dtype),
                  fn=_plain_fn),
        LayerSpec("layer3",
                  make=functools.partial(_PosEmbed, seq_len=time_steps + 1,
                                         embed_dim=embed_dim,
                                         dropout_rate=dropout_rate,
                                         dtype=dtype),
                  fn=_train_fn),
    ]
    for i in range(12):
        specs.append(LayerSpec(
            f"layer{4 + i}",
            make=functools.partial(PreLNBlock, embed_dim=embed_dim,
                                   num_heads=num_heads, mlp_dim=mlp_dim,
                                   dtype=dtype),
            fn=_train_fn))
    specs.append(LayerSpec(
        "layer16", make=functools.partial(nn.LayerNorm, dtype=dtype),
        fn=_cls_norm_fn))
    specs.append(LayerSpec(
        "layer17", make=functools.partial(nn.Dense, features=num_classes,
                                          dtype=dtype),
        fn=_plain_fn))
    assert len(specs) == 17
    return tuple(specs)
