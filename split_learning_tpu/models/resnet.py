"""ResNet-50 as indexed layers (north-star BASELINE.json config #3).

Fresh design — the reference has no ResNet; the 3-way-split target config
needs one.  To honor the split-layer contract (every layer index is a
valid cut point with a single streaming activation), each bottleneck
residual block is ONE layer — the same granularity the reference uses for
transformer blocks (``src/model/BERT_AGNEWS.py:185-200``, one block per
index).  CIFAR stem (3x3 stride 1, no maxpool):

1 = stem conv, 2 = stem BN, 3 = relu, 4..19 = 16 bottleneck blocks
(3-4-6-3 geometry, strides 2 at stage entries), 20 = global average
pool + flatten, 21 = linear head — 21 layers.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model, relu_fn, batchnorm_fn,
    module_train_fn as _train_fn,
)


class Bottleneck(nn.Module):
    """1x1 reduce -> 3x3 -> 1x1 expand with projection shortcut."""
    features: int                  # bottleneck width; out = 4x
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5,
                               dtype=self.dtype,
                               use_running_average=not train)
        out_ch = self.features * 4
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = nn.relu(bn(name="bn1")(y))
        y = conv(self.features, (3, 3), strides=(self.strides,) * 2,
                 padding=1, name="conv2")(y)
        y = nn.relu(bn(name="bn2")(y))
        y = conv(out_ch, (1, 1), name="conv3")(y)
        y = bn(name="bn3")(y)
        if residual.shape[-1] != out_ch or self.strides != 1:
            residual = conv(out_ch, (1, 1), strides=(self.strides,) * 2,
                            name="proj")(x)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(y + residual)


def _avgpool_flatten(_, x, train):
    return jnp.mean(x, axis=(1, 2))


def _resnet50_specs(num_classes: int, dtype=jnp.float32) -> tuple:
    bn = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5,
                           dtype=dtype)
    specs = [
        LayerSpec("layer1", make=functools.partial(
            nn.Conv, features=64, kernel_size=(3, 3), padding=1,
            use_bias=False, dtype=dtype)),
        LayerSpec("layer2", make=bn, fn=batchnorm_fn),
        LayerSpec("layer3", fn=relu_fn),
    ]
    idx = 3

    def blk(features, strides):
        nonlocal idx
        idx += 1
        specs.append(LayerSpec(
            f"layer{idx}",
            make=functools.partial(Bottleneck, features=features,
                                   strides=strides, dtype=dtype),
            fn=_train_fn))

    for features, n_blocks, first_stride in (
            (64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)):
        for i in range(n_blocks):
            blk(features, first_stride if i == 0 else 1)
    specs.append(LayerSpec(f"layer{idx + 1}", fn=_avgpool_flatten))
    specs.append(LayerSpec(f"layer{idx + 2}", make=functools.partial(
        nn.Dense, features=num_classes, dtype=dtype)))
    assert len(specs) == 21
    return tuple(specs)


@register_model("ResNet50_CIFAR100")
def resnet50_cifar100(dtype=jnp.float32) -> tuple:
    """(B, 32, 32, 3) NHWC -> 100 classes, 21 layers."""
    return _resnet50_specs(100, dtype=dtype)


@register_model("ResNet50_CIFAR10")
def resnet50_cifar10(dtype=jnp.float32) -> tuple:
    return _resnet50_specs(10, dtype=dtype)
