"""BERT-base as 15 macro-layers for AG-News (and the 6-label emotion
variant).

Indexing parity with the reference (``/root/reference/src/model/
BERT_AGNEWS.py:185-200``): layer 1 = embeddings, layers 2-13 = encoder
blocks, 14 = CLS pooler, 15 = classifier.  ``BERT_EMOTION`` mirrors the
Vanilla_SL variant's 6-label model at the same macro granularity.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model, module_train_fn as _train_fn,
)
from split_learning_tpu.models.transformer import (
    BertAttentionSublayer, BertBlock, BertEmbeddings, BertFfnSublayer,
    Pooler, ClassifierHead,
)

_PAD_ID = 0  # [PAD] is id 0 in BERT vocabs (wordpiece.py, HF convention)


def _embed_fn(mod, input_ids, train):
    """Layer 1: derive the attention padding mask from the token ids
    (reference parity: the AGNEWS pipeline carries attention_mask
    end-to-end, ``src/dataset/AGNEWS.py:22-30``) and thread it alongside
    the hidden states so it crosses split/stage boundaries with the
    activations."""
    mask = (input_ids != _PAD_ID)
    return mod(input_ids, train=train), mask


def _block_fn(mod, xm, train):
    """Encoder block on (hidden, mask): padded key positions are not
    attended (broadcast (B, 1, 1, S_kv) boolean mask)."""
    x, mask = xm
    return mod(x, mask=mask[:, None, None, :], train=train), mask


def _pooler_fn(mod, xm, train):
    x, _ = xm  # CLS pooling: the mask's job is done
    return mod(x)


def _bert_specs(num_labels: int, vocab_size: int = 28996,
                hidden_size: int = 768, num_heads: int = 12,
                intermediate_size: int = 3072,
                max_position_embeddings: int = 512, n_block: int = 12,
                dropout_rate: float = 0.1, dtype=jnp.float32,
                fine_grained: bool = False) -> tuple:
    """``fine_grained=False``: 15 macro layers (1 embeddings, 2-13
    blocks, 14 pooler, 15 classifier — ``src/model/BERT_AGNEWS.py:
    185-200``).  ``fine_grained=True``: 27 per-sublayer layers — each
    block splits into an attention sublayer and an FFN sublayer, so cut
    points land INSIDE blocks (reference BERT_EMOTION,
    ``other/Vanilla_SL/src/model/BERT_EMOTION.py:183-185``: 1
    embeddings, 2-25 alternating attn/ffn, 26 pooler, 27 classifier).
    """
    specs = [LayerSpec(
        name="layer1",
        make=functools.partial(
            BertEmbeddings, vocab_size=vocab_size, hidden_size=hidden_size,
            max_position_embeddings=max_position_embeddings,
            dropout_rate=dropout_rate, dtype=dtype),
        fn=_embed_fn)]
    idx = 2
    for _ in range(n_block):
        if fine_grained:
            specs.append(LayerSpec(
                name=f"layer{idx}",
                make=functools.partial(
                    BertAttentionSublayer, hidden_size=hidden_size,
                    num_heads=num_heads, dropout_rate=dropout_rate,
                    dtype=dtype),
                fn=_block_fn))
            idx += 1
            specs.append(LayerSpec(
                name=f"layer{idx}",
                make=functools.partial(
                    BertFfnSublayer, hidden_size=hidden_size,
                    intermediate_size=intermediate_size,
                    dropout_rate=dropout_rate, dtype=dtype),
                fn=_block_fn))
            idx += 1
        else:
            specs.append(LayerSpec(
                name=f"layer{idx}",
                make=functools.partial(
                    BertBlock, hidden_size=hidden_size,
                    num_heads=num_heads,
                    intermediate_size=intermediate_size,
                    dropout_rate=dropout_rate, dtype=dtype),
                fn=_block_fn))
            idx += 1
    specs.append(LayerSpec(
        name=f"layer{idx}",
        make=functools.partial(Pooler, hidden_size=hidden_size, dtype=dtype),
        fn=_pooler_fn))
    specs.append(LayerSpec(
        name=f"layer{idx + 1}",
        make=functools.partial(ClassifierHead, num_labels=num_labels,
                               dropout_rate=dropout_rate, dtype=dtype),
        fn=_train_fn))
    return tuple(specs)


@register_model("BERT_AGNEWS")
def bert_agnews(dtype=jnp.float32, **kw) -> tuple:
    """AG-News: 4 classes, input (B, 128) int token ids."""
    return _bert_specs(4, dtype=dtype, **kw)


@register_model("BERT_EMOTION")
def bert_emotion(dtype=jnp.float32, **kw) -> tuple:
    """Emotion: 6 classes (Vanilla_SL variant).  Pass
    ``fine_grained=True`` for the reference's 27 per-sublayer cut
    points (``other/Vanilla_SL/src/model/BERT_EMOTION.py:183-185``)."""
    return _bert_specs(6, dtype=dtype, **kw)
