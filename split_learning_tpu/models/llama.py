"""TinyLlama-style causal LM as indexed layers (north-star config #5).

Fresh design — the reference tops out at BERT-base/128 tokens; the
4-stage-pipeline target config needs a modern decoder.  LLaMA family
geometry: RMSNorm pre-norm blocks, rotary position embeddings, grouped-
query attention, SwiGLU MLP, untied LM head.  TinyLlama-1.1B defaults
(2048 hidden, 22 blocks, 32 Q / 4 KV heads, 5632 intermediate, 32000
vocab); tests pass tiny overrides through the same builder.

Split-layer contract: 1 = token embedding, 2..n_block+1 = decoder blocks,
n_block+2 = final RMSNorm, n_block+3 = LM head (25 layers at full size).
The streaming activation between any two stages is the (B, S, H) hidden
state — exactly what ``ppermute``/the wire carries.  Causality needs no
mask plumbing across stages: each block rebuilds its own causal mask from
the sequence length.

Loss: next-token CE — the labels tensor is the input shifted by the data
pipeline (``data/datasets.py`` TINYSTORIES provider), so the pipeline's
``softmax_cross_entropy`` path broadcasts over (B, S) unchanged.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from split_learning_tpu.models.split import (
    LayerSpec, register_model, module_plain_fn as _plain_fn,
)


def _rope(x: jnp.ndarray, positions: jnp.ndarray,
          base: float = 10000.0) -> jnp.ndarray:
    """Rotary embedding over the last dim of (B, S, H, D)."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (np.arange(0, d, 2) / d))
    freqs = positions[:, None].astype(jnp.float32) * inv_freq[None, :]
    cos = jnp.cos(freqs)[None, :, None, :]
    sin = jnp.sin(freqs)[None, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.reshape(x.shape).astype(x.dtype)


class LlamaAttention(nn.Module):
    """Causal GQA with RoPE.

    ``use_flash`` routes the score/softmax/value contraction through the
    fused Pallas kernel (``ops/flash_attention.py``) instead of the
    XLA einsum path — same math, O(S) memory.
    """
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    dtype: jnp.dtype = jnp.float32
    use_flash: bool = False
    # sequence-parallel mode (parallel/sequence.py): when set, this
    # module runs inside shard_map with `seq_axis` defined, x is the
    # LOCAL token block, RoPE positions offset by the global block
    # index, and attention goes through ring_attention
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        hd = self.hidden_size // self.num_heads
        dense = functools.partial(nn.Dense, use_bias=False,
                                  dtype=self.dtype)
        q = dense(self.num_heads * hd, name="q_proj")(x)
        k = dense(self.num_kv_heads * hd, name="k_proj")(x)
        v = dense(self.num_kv_heads * hd, name="v_proj")(x)
        q = q.reshape(b, s, self.num_heads, hd)
        k = k.reshape(b, s, self.num_kv_heads, hd)
        v = v.reshape(b, s, self.num_kv_heads, hd)

        if self.seq_axis is not None:
            import jax
            pos = jax.lax.axis_index(self.seq_axis) * s + jnp.arange(s)
        else:
            pos = jnp.arange(s)
        q, k = _rope(q, pos), _rope(k, pos)
        rep = self.num_heads // self.num_kv_heads
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

        if self.seq_axis is not None:
            from split_learning_tpu.parallel.sequence import ring_attention
            out = ring_attention(q, k, v, axis_name=self.seq_axis,
                                 causal=True).reshape(b, s, -1)
        elif self.use_flash:
            from split_learning_tpu.ops.flash_attention import (
                flash_attention,
            )
            out = flash_attention(q, k, v, causal=True).reshape(b, s, -1)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
            mask = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(mask[None, None], scores, -1e30)
            probs = nn.softmax(
                scores.astype(jnp.float32)).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, -1)
        return dense(self.hidden_size, name="o_proj")(out)


class LlamaBlock(nn.Module):
    """Pre-RMSNorm: x + attn(norm(x)); x + swiglu(norm(x))."""
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    dtype: jnp.dtype = jnp.float32
    use_flash: bool = False
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        h = nn.RMSNorm(epsilon=1e-5, dtype=self.dtype,
                       name="input_norm")(x)
        x = x + LlamaAttention(
            hidden_size=self.hidden_size, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, dtype=self.dtype,
            use_flash=self.use_flash, seq_axis=self.seq_axis,
            name="attention")(h)
        h = nn.RMSNorm(epsilon=1e-5, dtype=self.dtype,
                       name="post_norm")(x)
        dense = functools.partial(nn.Dense, use_bias=False,
                                  dtype=self.dtype)
        gate = nn.silu(dense(self.intermediate_size, name="gate_proj")(h))
        up = dense(self.intermediate_size, name="up_proj")(h)
        return x + dense(self.hidden_size, name="down_proj")(gate * up)


class MoELlamaBlock(nn.Module):
    """LlamaBlock with the dense SwiGLU MLP swapped for a top-k
    mixture-of-experts FFN (:class:`~split_learning_tpu.parallel.expert.
    MoEMLP`) — the expert-parallel scale-out variant (no reference
    counterpart; SURVEY.md §2.2 EP row)."""
    hidden_size: int
    num_heads: int
    num_kv_heads: int
    intermediate_size: int
    num_experts: int = 8
    k: int = 2
    dtype: jnp.dtype = jnp.float32
    use_flash: bool = False
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        from split_learning_tpu.parallel.expert import MoEMLP
        h = nn.RMSNorm(epsilon=1e-5, dtype=self.dtype,
                       name="input_norm")(x)
        x = x + LlamaAttention(
            hidden_size=self.hidden_size, num_heads=self.num_heads,
            num_kv_heads=self.num_kv_heads, dtype=self.dtype,
            use_flash=self.use_flash, seq_axis=self.seq_axis,
            name="attention")(h)
        h = nn.RMSNorm(epsilon=1e-5, dtype=self.dtype,
                       name="post_norm")(x)
        return x + MoEMLP(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_experts=self.num_experts, k=self.k, dtype=self.dtype,
            name="moe")(h)


def _llama_specs(vocab_size: int = 32000, hidden_size: int = 2048,
                 num_heads: int = 32, num_kv_heads: int = 4,
                 intermediate_size: int = 5632, n_block: int = 22,
                 use_flash: bool = False, dtype=jnp.float32,
                 num_experts: int = 0, k: int = 2,
                 seq_axis: str | None = None) -> tuple:
    specs = [LayerSpec("layer1", make=functools.partial(
        nn.Embed, num_embeddings=vocab_size, features=hidden_size,
        dtype=dtype), fn=_plain_fn)]
    for i in range(n_block):
        if num_experts > 0:
            block = functools.partial(
                MoELlamaBlock, hidden_size=hidden_size,
                num_heads=num_heads, num_kv_heads=num_kv_heads,
                intermediate_size=intermediate_size,
                num_experts=num_experts, k=k, use_flash=use_flash,
                seq_axis=seq_axis, dtype=dtype)
        else:
            block = functools.partial(
                LlamaBlock, hidden_size=hidden_size, num_heads=num_heads,
                num_kv_heads=num_kv_heads,
                intermediate_size=intermediate_size, use_flash=use_flash,
                seq_axis=seq_axis, dtype=dtype)
        specs.append(LayerSpec(f"layer{2 + i}", make=block, fn=_plain_fn))
    specs.append(LayerSpec(f"layer{2 + n_block}",
                           make=functools.partial(nn.RMSNorm, epsilon=1e-5,
                                                  dtype=dtype),
                           fn=_plain_fn))
    specs.append(LayerSpec(f"layer{3 + n_block}", make=functools.partial(
        nn.Dense, features=vocab_size, use_bias=False, dtype=dtype),
        fn=_plain_fn))
    return tuple(specs)


@register_model("TinyLlama_TINYSTORIES")
def tinyllama_tinystories(dtype=jnp.float32, **kw) -> tuple:
    """TinyLlama-1.1B geometry; input (B, S) int32 token ids, output
    (B, S, vocab) next-token logits.  25 layers at full size."""
    return _llama_specs(dtype=dtype, **kw)


@register_model("TinyLlamaMoE_TINYSTORIES")
def tinyllama_moe_tinystories(dtype=jnp.float32, num_experts: int = 8,
                              **kw) -> tuple:
    """Sparse-MoE variant: every decoder block's MLP is a top-k
    mixture of ``num_experts`` SwiGLU experts, shardable over an
    ``expert`` mesh axis (``parallel/expert.py``).  Same split-layer
    contract as the dense model."""
    return _llama_specs(dtype=dtype, num_experts=num_experts, **kw)
