"""MobileNetv1 (Vanilla_SL variant flavor) as 84 indexed layers.

Layer-for-layer indexing parity with ``/root/reference/other/Vanilla_SL/
src/model/MobileNetv1_CIFAR10.py:5-185``: 27 conv→bn→relu triplets
(the variant's "MobileNet" uses full 3x3 convs + 1x1 pointwise convs,
not depthwise grouping — reproduced as-is for behavioral parity), then
maxpool (82), flatten (83), linear head (84).  Strides 2 at triplets
4/8/12/24 take 32px -> 2px before the pool.  NHWC + bfloat16-capable.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model, relu_fn, maxpool2_fn, flatten_fn,
    batchnorm_fn,
)

#: (out_channels, kernel, stride) per conv triplet — 27 triplets
_CONVS = [
    (32, 3, 1), (32, 3, 1), (64, 1, 1),
    (64, 3, 2), (128, 1, 1), (128, 3, 1), (128, 1, 1),
    (128, 3, 2), (256, 1, 1), (256, 3, 1), (256, 1, 1),
    (256, 3, 2), (512, 1, 1),
    (512, 3, 1), (512, 1, 1), (512, 3, 1), (512, 1, 1),
    (512, 3, 1), (512, 1, 1), (512, 3, 1), (512, 1, 1),
    (512, 3, 1), (512, 1, 1),
    (512, 3, 2), (1024, 1, 1), (1024, 3, 1), (1024, 1, 1),
]


def _mobilenet_specs(num_classes: int, dtype=jnp.float32) -> tuple:
    bn = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5,
                           dtype=dtype)
    specs: list[LayerSpec] = []
    idx = 0

    def add(make=None, fn=None):
        nonlocal idx
        idx += 1
        specs.append(LayerSpec(name=f"layer{idx}", make=make, fn=fn))

    for out_ch, k, s in _CONVS:
        add(make=functools.partial(
            nn.Conv, features=out_ch, kernel_size=(k, k), strides=(s, s),
            padding=(1 if k == 3 else 0), dtype=dtype))
        add(make=bn, fn=batchnorm_fn)
        add(fn=relu_fn)
    add(fn=maxpool2_fn)
    add(fn=flatten_fn)
    add(make=functools.partial(nn.Dense, features=num_classes, dtype=dtype))
    assert len(specs) == 84
    return tuple(specs)


@register_model("MobileNetv1_CIFAR10")
def mobilenet_cifar10(dtype=jnp.float32) -> tuple:
    """CIFAR-10: (B, 32, 32, 3) NHWC, 10 classes, 84 layers."""
    return _mobilenet_specs(10, dtype=dtype)


@register_model("MobileNetv1_MNIST")
def mobilenet_mnist(dtype=jnp.float32) -> tuple:
    """MNIST: (B, 28, 28, 1) NHWC, 10 classes, 84 layers."""
    return _mobilenet_specs(10, dtype=dtype)
