"""VGG16 (+BatchNorm) as 52 individually indexed layers.

Layer-for-layer the same indexing contract as the reference
(``/root/reference/src/model/VGG16_CIFAR10.py:4-117``: conv/bn/relu/pool/
flatten/dropout/linear each occupy one index, 52 total), expressed as one
declarative spec list.  NHWC layout and optional bfloat16 compute dtype —
the MXU-friendly choices — instead of the reference's NCHW float32.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax.numpy as jnp

from split_learning_tpu.models.split import (
    LayerSpec, register_model, relu_fn, maxpool2_fn, flatten_fn,
    dropout_layer, batchnorm_fn,
)

# (out_channels, convs per block, pool after block?) — CIFAR: 5 pools
# (52 layers, 32px -> 1px); MNIST: 4 pools (51 layers, 28px -> 1px),
# matching other/Vanilla_SL/src/model/VGG16_MNIST.py's layer indices.
_VGG16_CIFAR_CFG = [(64, 2, True), (128, 2, True), (256, 3, True),
                    (512, 3, True), (512, 3, True)]
_VGG16_MNIST_CFG = [(64, 2, True), (128, 2, True), (256, 3, True),
                    (512, 3, True), (512, 3, False)]


def _vgg_specs(num_classes: int, cfg=None, dtype=jnp.float32) -> tuple:
    conv = functools.partial(nn.Conv, kernel_size=(3, 3), strides=(1, 1),
                             padding=1, dtype=dtype)
    bn = functools.partial(nn.BatchNorm, momentum=0.9, epsilon=1e-5,
                           dtype=dtype)
    dense = functools.partial(nn.Dense, dtype=dtype)
    cfg = cfg or _VGG16_CIFAR_CFG

    specs: list[LayerSpec] = []
    idx = 0

    def add(make=None, fn=None):
        nonlocal idx
        idx += 1
        specs.append(LayerSpec(name=f"layer{idx}", make=make, fn=fn))

    for out_ch, n_convs, pool in cfg:
        for _ in range(n_convs):
            add(make=functools.partial(conv, features=out_ch))
            add(make=bn, fn=batchnorm_fn)
            add(fn=relu_fn)
        if pool:
            add(fn=maxpool2_fn)

    add(fn=flatten_fn)
    dmake, dfn = dropout_layer(0.5)
    add(make=dmake, fn=dfn)
    add(make=functools.partial(dense, features=4096))
    add(fn=relu_fn)
    dmake, dfn = dropout_layer(0.5)
    add(make=dmake, fn=dfn)
    add(make=functools.partial(dense, features=4096))
    add(fn=relu_fn)
    add(make=functools.partial(dense, features=num_classes))
    return tuple(specs)


@register_model("VGG16_CIFAR10")
def vgg16_cifar10(dtype=jnp.float32) -> tuple:
    """CIFAR-10 VGG16: input (B, 32, 32, 3) NHWC, 10 classes, 52 layers."""
    specs = _vgg_specs(10, dtype=dtype)
    assert len(specs) == 52
    return specs


@register_model("VGG16_MNIST")
def vgg16_mnist(dtype=jnp.float32) -> tuple:
    """MNIST VGG16: input (B, 28, 28, 1) NHWC, 10 classes, 51 layers
    (4 pools; 28 -> 14 -> 7 -> 3 -> 1)."""
    specs = _vgg_specs(10, cfg=_VGG16_MNIST_CFG, dtype=dtype)
    assert len(specs) == 51
    return specs


@register_model("VGG16_CIFAR100")
def vgg16_cifar100(dtype=jnp.float32) -> tuple:
    return _vgg_specs(100, dtype=dtype)
